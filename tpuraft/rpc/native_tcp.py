"""asyncio bindings for the C++ epoll transport (native/transport.cc).

Reference parity: the seam where SOFABolt rides Netty's *native epoll*
transport (SURVEY.md §3.4 "Netty native transport") — the C++ event
loop owns every socket (listen/accept, pooled outbound connections,
framing, write queues) on its own I/O thread, and asyncio only ever
sees complete frames, delivered through an eventfd registered with
``loop.add_reader``.  Wire format is identical to tpuraft/rpc/tcp.py,
so :class:`NativeTcpRpcServer` serves pure-Python ``TcpTransport``
clients and vice versa.

Build: ``make -C native``; :func:`ensure_built` does it on demand.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import struct
import threading
from typing import Any, Callable, Optional

from tpuraft.errors import RaftError, Status
from tpuraft.rpc.messages import decode_message, encode_message
from tpuraft.rpc.transport import RpcError, RpcServer, TransportBase

LOG = logging.getLogger(__name__)

_LIB_NAME = "libtpuraft_transport.so"
_F_RESPONSE = 1
_F_ERROR = 2
_EV_FRAME = 1
_EV_CLOSED = 2


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), os.pardir, "native")


def lib_path() -> str:
    return os.environ.get(
        "TPURAFT_NATIVE_TRANSPORT_LIB",
        os.path.normpath(os.path.join(_native_dir(), _LIB_NAME)))


def ensure_built(timeout: float = 120.0) -> str:
    from tpuraft.util.native_build import ensure_built as _eb
    return _eb(_native_dir(), lib_path(), timeout=timeout)


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(lib_path())
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tnt_create.restype = ctypes.c_void_p
            lib.tnt_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.tnt_destroy.argtypes = [ctypes.c_void_p]
            lib.tnt_notify_fd.restype = ctypes.c_int
            lib.tnt_notify_fd.argtypes = [ctypes.c_void_p]
            lib.tnt_listen.restype = ctypes.c_int
            lib.tnt_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_char_p,
                                       ctypes.c_int]
            lib.tnt_send_to.restype = ctypes.c_int64
            lib.tnt_send_to.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_uint8,
                                        ctypes.c_char_p, ctypes.c_int64,
                                        ctypes.c_char_p, ctypes.c_int]
            lib.tnt_send_conn.restype = ctypes.c_int
            lib.tnt_send_conn.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.c_uint64, ctypes.c_uint8,
                                          ctypes.c_char_p, ctypes.c_int64]
            lib.tnt_drop_endpoint.restype = ctypes.c_int
            lib.tnt_drop_endpoint.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p]
            lib.tnt_next_event.restype = ctypes.c_int
            lib.tnt_next_event.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(u8p),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
                ctypes.c_int]
            lib.tnt_free.argtypes = [u8p]
            _lib = lib
        return _lib


class _NativeCtx:
    """One C++ event-loop context wired into the running asyncio loop.

    Owner registers callbacks; frames/closes arrive on the asyncio
    thread via the notify eventfd, so no locking is needed above.
    """

    def __init__(self,
                 on_frame: Callable[[int, str, int, int, bytes], None],
                 on_closed: Callable[[int, str], None]):
        ensure_built()
        self._lib = _load()
        err = ctypes.create_string_buffer(256)
        self._h = self._lib.tnt_create(err, len(err))
        if not self._h:
            raise OSError(f"tnt_create: {err.value.decode()}")
        self._on_frame = on_frame
        self._on_closed = on_closed
        self._fd = self._lib.tnt_notify_fd(self._h)
        self._loop = asyncio.get_running_loop()
        self._loop.add_reader(self._fd, self._drain)
        self._closed = False

    def listen(self, host: str, port: int) -> int:
        err = ctypes.create_string_buffer(256)
        bound = self._lib.tnt_listen(self._h, host.encode(), port, err,
                                     len(err))
        if bound < 0:
            raise OSError(f"listen {host}:{port}: {err.value.decode()}")
        return bound

    def send_to(self, endpoint: str, seq: int, flags: int,
                payload: bytes) -> int:
        err = ctypes.create_string_buffer(256)
        conn_id = self._lib.tnt_send_to(self._h, endpoint.encode(), seq,
                                        flags, payload, len(payload), err,
                                        len(err))
        if conn_id < 0:
            raise RpcError(Status.error(
                RaftError.EHOSTDOWN,
                f"send to {endpoint}: {err.value.decode()}"))
        return conn_id

    def send_conn(self, conn_id: int, seq: int, flags: int,
                  payload: bytes) -> bool:
        return self._lib.tnt_send_conn(self._h, conn_id, seq, flags,
                                       payload, len(payload)) == 0

    def drop_endpoint(self, endpoint: str) -> None:
        self._lib.tnt_drop_endpoint(self._h, endpoint.encode())

    def _drain(self) -> None:
        """Dequeue every pending event (called by add_reader)."""
        lib = self._lib
        ev_type = ctypes.c_int()
        conn_id = ctypes.c_int64()
        seq = ctypes.c_uint64()
        flags = ctypes.c_uint8()
        payload = ctypes.POINTER(ctypes.c_uint8)()
        plen = ctypes.c_int64()
        endpoint = ctypes.create_string_buffer(128)
        while not self._closed and lib.tnt_next_event(
                self._h, ctypes.byref(ev_type), ctypes.byref(conn_id),
                ctypes.byref(seq), ctypes.byref(flags),
                ctypes.byref(payload), ctypes.byref(plen), endpoint,
                len(endpoint)):
            data = ctypes.string_at(payload, plen.value) if plen.value \
                else b""
            lib.tnt_free(payload)
            ep = endpoint.value.decode()
            try:
                if ev_type.value == _EV_FRAME:
                    self._on_frame(conn_id.value, ep, seq.value,
                                   flags.value, data)
                elif ev_type.value == _EV_CLOSED:
                    self._on_closed(conn_id.value, ep)
            except Exception:  # noqa: BLE001 — callback bug must not
                LOG.exception("native transport event callback failed")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._loop.remove_reader(self._fd)
        self._lib.tnt_destroy(self._h)
        self._h = None


class NativeTcpRpcServer(RpcServer):
    """Server side: the C++ engine listens/accepts/frames; handlers run
    as asyncio tasks; responses go back over the originating connection.
    Drop-in replacement for TcpRpcServer (same handler registry)."""

    def __init__(self, endpoint: str, bind_host: Optional[str] = None):
        super().__init__(endpoint)
        self._bind_host = bind_host
        self._ctx: Optional[_NativeCtx] = None
        self._bound_port = 0
        self._tasks: set[asyncio.Task] = set()

    @property
    def bound_port(self) -> int:
        return self._bound_port

    async def start(self) -> None:
        host, port_s = self.endpoint.rsplit(":", 1)
        ctx = _NativeCtx(self._on_frame, lambda cid, ep: None)
        try:
            self._bound_port = ctx.listen(self._bind_host or host,
                                          int(port_s))
        except OSError:
            ctx.close()  # don't leak the io thread + fds on bind failure
            raise
        self._ctx = ctx
        self.running = True

    async def stop(self) -> None:
        self.running = False
        for t in list(self._tasks):
            t.cancel()
        for t in list(self._tasks):
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks.clear()
        if self._ctx is not None:
            self._ctx.close()
            self._ctx = None

    def _on_frame(self, conn_id: int, endpoint: str, seq: int, flags: int,
                  payload: bytes) -> None:
        # concurrent dispatch, same rationale as TcpRpcServer: a slow
        # handler must not head-of-line-block heartbeats
        t = asyncio.ensure_future(self._serve_one(conn_id, seq, payload))
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _serve_one(self, conn_id: int, seq: int,
                         payload: bytes) -> None:
        flags, blob = await self.serve_framed_payload(
            seq, payload, _F_RESPONSE, _F_ERROR)
        if self._ctx is not None:
            self._ctx.send_conn(conn_id, seq, flags, blob)


class NativeTcpTransport(TransportBase):
    """Client side: pooled pipelined connections owned by the C++
    engine; request/response correlation by sequence number up here.
    Drop-in replacement for TcpTransport."""

    def __init__(self, endpoint: str = "client:0",
                 default_timeout_ms: float = 1000.0):
        self.endpoint = endpoint
        self._timeout_ms = default_timeout_ms
        self._ctx: Optional[_NativeCtx] = None
        self._seq = 0
        # (conn_id, seq) -> future; conn failure fails only its own calls
        self._pending: dict[tuple[int, int], asyncio.Future] = {}

    def _ensure_ctx(self) -> _NativeCtx:
        if self._ctx is None:
            self._ctx = _NativeCtx(self._on_frame, self._on_closed)
        return self._ctx

    def _on_frame(self, conn_id: int, endpoint: str, seq: int, flags: int,
                  payload: bytes) -> None:
        fut = self._pending.pop((conn_id, seq), None)
        if fut is None or fut.done():
            return
        try:
            msg = decode_message(payload)
        except Exception as e:  # noqa: BLE001 — protocol desync
            fut.set_exception(RpcError(Status.error(
                RaftError.EINTERNAL, f"undecodable response: {e!r}")))
            if self._ctx is not None:
                self._ctx.drop_endpoint(endpoint)
            return
        if flags & _F_ERROR:
            fut.set_exception(RpcError(Status(msg.code, msg.msg)))
        else:
            fut.set_result(msg)

    def _on_closed(self, conn_id: int, endpoint: str) -> None:
        status = Status.error(RaftError.EHOSTDOWN,
                              f"connection to {endpoint} lost")
        for key in [k for k in self._pending if k[0] == conn_id]:
            fut = self._pending.pop(key)
            if not fut.done():
                fut.set_exception(RpcError(status))

    async def call(self, dst: str, method: str, request: Any,
                   timeout_ms: Optional[float] = None) -> Any:
        timeout = (timeout_ms if timeout_ms is not None
                   else self._timeout_ms) / 1000.0
        ctx = self._ensure_ctx()
        m = method.encode()
        payload = struct.pack("<H", len(m)) + m + encode_message(request)
        self._seq += 1
        seq = self._seq
        conn_id = ctx.send_to(dst, seq, 0, payload)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        # safe: _drain runs on this same loop thread, never mid-statement
        self._pending[(conn_id, seq)] = fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop((conn_id, seq), None)
            raise RpcError(Status.error(
                RaftError.ETIMEDOUT, f"{method} to {dst}"))

    async def close(self) -> None:
        if self._ctx is not None:
            self._ctx.close()
            self._ctx = None
        status = Status.error(RaftError.ESHUTDOWN, "transport closed")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(RpcError(status))
        self._pending.clear()
