"""Transport: async RPC interface + in-process loopback with fault injection.

Reference parity: ``core:rpc/RaftClientService`` / processors bound to one
shared RpcServer multiplexing many groups (SURVEY.md §2 L2, §3.1).  The
in-proc implementation is the analog of the reference's signature test
pattern — ``TestCluster``: N real nodes in one process, real protocol,
loopback "network" with kill/partition/delay/drop injection (§5).

Routing: requests carry (group_id, peer_id); an :class:`RpcServer`
registered per endpoint dispatches to per-group handlers (NodeManager).
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Awaitable, Callable, Optional

from tpuraft.errors import RaftError, Status


class RpcError(Exception):
    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status


def is_no_method(e: RpcError) -> bool:
    """True when the receiver has no handler for the requested method —
    the capability probe the batch planes (send plane, heartbeat hub)
    key their per-item fallback on.  The dedicated ENOMETHOD code is
    authoritative; the substring is a compat net for receivers older
    than the code itself."""
    return (e.status.code == RaftError.ENOMETHOD
            or "no handler" in e.status.error_msg)


class RpcServer:
    """One per process endpoint; multiplexes all raft groups on it.

    Handlers: method name -> async fn(request) -> response.  The node
    manager registers one handler set and routes by request.group_id
    (reference: NodeManager + per-request processors on a shared server).
    """

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._handlers: dict[str, Callable[[Any], Awaitable[Any]]] = {}
        self.running = False

    def register(self, method: str, handler: Callable[[Any], Awaitable[Any]]) -> None:
        self._handlers[method] = handler

    async def dispatch(self, method: str, request: Any) -> Any:
        h = self._handlers.get(method)
        if h is None:
            raise RpcError(Status.error(RaftError.ENOMETHOD, f"no handler {method}"))
        return await h(request)

    async def serve_framed_payload(self, seq: int, payload: bytes,
                                   response_flag: int, error_flag: int
                                   ) -> tuple[int, bytes]:
        """Decode a wire request payload (u16 method_len | method |
        message), dispatch it, and encode the response envelope.
        Shared by every framed transport backend (asyncio TCP, native
        epoll); returns (flags, encoded_response)."""
        import logging
        import struct

        from tpuraft.rpc.messages import (
            ErrorResponse,
            decode_message,
            encode_message,
        )

        flags = response_flag
        try:
            (mlen,) = struct.unpack_from("<H", payload, 0)
            method = payload[2:2 + mlen].decode()
            request = decode_message(memoryview(payload)[2 + mlen:])
            response = await self.dispatch(method, request)
        except asyncio.CancelledError:
            raise
        except RpcError as e:
            flags |= error_flag
            response = ErrorResponse(e.status.code, e.status.error_msg)
        except Exception as e:  # noqa: BLE001 — handler bug must not kill conn
            logging.getLogger(__name__).exception(
                "rpc handler failed (seq=%d)", seq)
            flags |= error_flag
            response = ErrorResponse(int(RaftError.EINTERNAL), repr(e))
        try:
            blob = encode_message(response)
        except Exception as e:  # noqa: BLE001
            flags |= error_flag
            blob = encode_message(
                ErrorResponse(int(RaftError.EINTERNAL),
                              f"unencodable response: {e!r}"))
        return flags, blob


class InProcNetwork:
    """Shared fabric for in-process transports; owns fault injection.

    Test API (TestCluster-style):
      net.partition({"a:1"}, {"b:1","c:1"})  — split-brain
      net.isolate("a:1") / net.heal()
      net.set_delay_ms(5), net.set_drop_rate(0.1)
      net.stop_endpoint(ep) / start_endpoint(ep)  — crash/restart
    """

    def __init__(self) -> None:
        self._servers: dict[str, RpcServer] = {}
        self._blocked_pairs: set[tuple[str, str]] = set()
        self._down: set[str] = set()
        self.delay_ms: float = 0.0
        self.drop_rate: float = 0.0
        self.duplicate_rate: float = 0.0
        self.reorder_rate: float = 0.0
        self.reorder_max_delay_ms: float = 10.0
        self._rng = random.Random(0)
        # geo shaping: a NetworkTopology (tpuraft/rpc/topology.py) adds
        # per-link zone x zone latency/jitter/loss/bandwidth on top of
        # the global knobs; healed separately via heal_topology()
        self.topology = None

    # -- server registry -----------------------------------------------------

    def bind(self, server: RpcServer) -> None:
        self._servers[server.endpoint] = server
        server.running = True

    def unbind(self, endpoint: str) -> None:
        s = self._servers.pop(endpoint, None)
        if s:
            s.running = False

    # -- fault injection -----------------------------------------------------

    def partition(self, side_a: set[str], side_b: set[str]) -> None:
        for a in side_a:
            for b in side_b:
                self._blocked_pairs.add((a, b))
                self._blocked_pairs.add((b, a))

    def partition_one_way(self, src: set[str], dst: set[str]) -> None:
        """Asymmetric partition: src -> dst dropped, dst -> src flows."""
        for a in src:
            for b in dst:
                self._blocked_pairs.add((a, b))

    def isolate(self, endpoint: str) -> None:
        others = set(self._servers) - {endpoint}
        self.partition({endpoint}, others)

    def heal(self) -> None:
        """Heal the NEMESIS layer only (partitions); the topology's
        shape and dynamic events survive — see heal_topology()."""
        self._blocked_pairs.clear()

    def set_topology(self, topology) -> None:
        self.topology = topology

    def heal_topology(self) -> None:
        """Clear the topology's DYNAMIC events (degrades / zone
        partitions / flaps); nemesis partitions and the base zone
        matrix stay."""
        if self.topology is not None:
            self.topology.heal_events()

    def stop_endpoint(self, endpoint: str) -> None:
        self._down.add(endpoint)

    def start_endpoint(self, endpoint: str) -> None:
        self._down.discard(endpoint)

    def set_delay_ms(self, ms: float) -> None:
        self.delay_ms = ms

    def set_drop_rate(self, rate: float) -> None:
        self.drop_rate = rate

    def set_duplicate_rate(self, rate: float) -> None:
        """Deliver (and execute) a frame twice with probability ``rate``;
        the duplicate's response is discarded."""
        self.duplicate_rate = rate

    def set_reorder(self, rate: float, max_delay_ms: float = 10.0) -> None:
        """Hold a frame for a seeded random bounded interval with
        probability ``rate`` so later frames overtake it."""
        self.reorder_rate = rate
        self.reorder_max_delay_ms = max_delay_ms

    # -- the "wire" ----------------------------------------------------------

    async def call(self, src: str, dst: str, method: str, request: Any,
                   timeout_ms: float) -> Any:
        if self.topology is not None:
            await self.topology.traverse(src, dst, request, timeout_ms)
        if self.reorder_rate and self._rng.random() < self.reorder_rate:
            await asyncio.sleep(
                self._rng.uniform(0.0, self.reorder_max_delay_ms) / 1000.0)
        if self.delay_ms:
            await asyncio.sleep(self.delay_ms / 1000.0)
        if (
            dst not in self._servers
            or dst in self._down
            or src in self._down
            or (src, dst) in self._blocked_pairs
            or (self.drop_rate and self._rng.random() < self.drop_rate)
        ):
            # unreachable: behave like a connect/request timeout
            await asyncio.sleep(min(timeout_ms, 50) / 1000.0)
            raise RpcError(
                Status.error(RaftError.EHOSTDOWN, f"{dst} unreachable from {src}"))
        server = self._servers[dst]
        if self.duplicate_rate and self._rng.random() < self.duplicate_rate:
            # the wire delivered the frame twice: the receiver executes
            # both copies; the duplicate's response evaporates
            dup = asyncio.ensure_future(asyncio.wait_for(
                server.dispatch(method, request), timeout_ms / 1000.0))
            dup.add_done_callback(lambda t: t.cancelled() or t.exception())
        try:
            return await asyncio.wait_for(
                server.dispatch(method, request), timeout_ms / 1000.0)
        except asyncio.TimeoutError:
            raise RpcError(Status.error(RaftError.ETIMEDOUT, f"{method} to {dst}"))


class TransportBase:
    """RaftClientService surface shared by every transport backend
    (in-proc loopback, TCP/DCN): ``call`` plus typed helpers."""

    endpoint: str

    async def call(self, dst: str, method: str, request: Any,
                   timeout_ms: Optional[float] = None) -> Any:
        raise NotImplementedError

    # typed helpers (reference: RaftClientService methods)

    async def append_entries(self, dst: str, req, timeout_ms=None):
        return await self.call(dst, "append_entries", req, timeout_ms)

    async def request_vote(self, dst: str, req, timeout_ms=None):
        return await self.call(dst, "request_vote", req, timeout_ms)

    async def install_snapshot(self, dst: str, req, timeout_ms=None):
        return await self.call(dst, "install_snapshot", req, timeout_ms)

    async def timeout_now(self, dst: str, req, timeout_ms=None):
        return await self.call(dst, "timeout_now", req, timeout_ms)

    async def read_index(self, dst: str, req, timeout_ms=None):
        return await self.call(dst, "read_index", req, timeout_ms)

    async def get_file(self, dst: str, req, timeout_ms=None):
        return await self.call(dst, "get_file", req, timeout_ms)


class InProcTransport(TransportBase):
    """The RaftClientService bound to one local endpoint."""

    def __init__(self, network: InProcNetwork, endpoint: str,
                 default_timeout_ms: float = 1000.0):
        self._net = network
        self.endpoint = endpoint
        self._timeout_ms = default_timeout_ms

    async def call(self, dst: str, method: str, request: Any,
                   timeout_ms: Optional[float] = None) -> Any:
        return await self._net.call(
            self.endpoint, dst, method, request,
            timeout_ms if timeout_ms is not None else self._timeout_ms)

