"""L2 RPC / transport (reference: core:rpc/ over SOFABolt/Netty — SURVEY.md §3.1).

Two implementations of one async interface:
  - :class:`tpuraft.rpc.transport.InProcTransport` — loopback, in one
    process, with fault injection (the TestCluster pattern, §5);
  - TCP transport (tpuraft.rpc.tcp_transport) with the binary codec for
    real deployments; the C++/gRPC DCN plane slots in behind the same
    interface.
"""

from tpuraft.rpc.messages import (
    AppendEntriesRequest,
    AppendEntriesResponse,
    RequestVoteRequest,
    RequestVoteResponse,
    InstallSnapshotRequest,
    InstallSnapshotResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
    ReadIndexRequest,
    ReadIndexResponse,
    GetFileRequest,
    GetFileResponse,
)
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer

__all__ = [
    "AppendEntriesRequest",
    "AppendEntriesResponse",
    "RequestVoteRequest",
    "RequestVoteResponse",
    "InstallSnapshotRequest",
    "InstallSnapshotResponse",
    "TimeoutNowRequest",
    "TimeoutNowResponse",
    "ReadIndexRequest",
    "ReadIndexResponse",
    "GetFileRequest",
    "GetFileResponse",
    "InProcNetwork",
    "InProcTransport",
    "RpcServer",
]
