"""FaultInjectingTransport: drops/delays/partitions over ANY transport.

The in-proc loopback has fault injection built in (the reference's
TestCluster pattern); this wrapper adds the same injection surface on
top of the real-socket transports (asyncio TCP, native epoll), so
chaos and adversarial drives run against production wire paths too.

A dropped call raises EHOSTDOWN after a short delay, modeling a lost
request the way the loopback does; the caller's retry/timeout machinery
reacts identically either way.

Beyond drop/delay/partition it injects the two other classic network
faults: **duplication** (the request is delivered and EXECUTED twice at
the receiver; the duplicate's response is discarded — receiver handlers
must be idempotent) and **bounded reordering** (a frame is held for a
random bounded interval so later frames overtake it).

Geo shaping: an attached :class:`~tpuraft.rpc.topology.NetworkTopology`
adds per-link (zone x zone, per-direction) latency/jitter/loss/
bandwidth on TOP of the global knobs.  The two fault layers compose and
heal independently: :meth:`FaultInjectingTransport.heal` clears only
the nemesis layer (per-destination blocks), while
:meth:`heal_topology` clears only the topology's DYNAMIC events
(degrades / zone partitions / flaps) — a nemesis action healing its
noise can no longer stomp the standing WAN shape, and vice versa.
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Optional

from tpuraft.errors import RaftError, Status
from tpuraft.rpc.topology import NetworkTopology
from tpuraft.rpc.transport import RpcError, TransportBase


class FaultInjectingTransport(TransportBase):
    def __init__(self, inner: TransportBase, seed: Optional[int] = None):
        self._inner = inner
        self.endpoint = inner.endpoint
        self._rng = random.Random(seed)
        self.drop_rate = 0.0
        self.delay_ms = 0.0
        self.duplicate_rate = 0.0
        self.reorder_rate = 0.0
        self.reorder_max_delay_ms = 10.0
        self._blocked_dsts: set[str] = set()
        # geo shaping: per-link latency/jitter/loss/bandwidth matrix;
        # usually one shared topology across every store's transport
        self.topology: Optional[NetworkTopology] = None

    # -- injection controls --------------------------------------------------

    def set_drop_rate(self, rate: float) -> None:
        self.drop_rate = rate

    def set_delay_ms(self, ms: float) -> None:
        self.delay_ms = ms

    def set_duplicate_rate(self, rate: float) -> None:
        """Each call is delivered (and executed) twice with probability
        ``rate``; the duplicate's response is discarded."""
        self.duplicate_rate = rate

    def set_reorder(self, rate: float, max_delay_ms: float = 10.0) -> None:
        """With probability ``rate``, hold a frame for a seeded random
        interval in (0, max_delay_ms] so later frames overtake it —
        bounded reordering, never starvation."""
        self.reorder_rate = rate
        self.reorder_max_delay_ms = max_delay_ms

    def block(self, dst: str) -> None:
        """Partition: calls to dst fail (one-way, from this side)."""
        self._blocked_dsts.add(dst)

    def unblock(self, dst: str) -> None:
        self._blocked_dsts.discard(dst)

    def set_topology(self, topology: Optional[NetworkTopology]) -> None:
        self.topology = topology

    def heal(self) -> None:
        """Heal the NEMESIS layer only: per-destination blocks.  The
        topology's standing shape AND its dynamic events survive — a
        noise action's heal must not silently flatten the WAN."""
        self._blocked_dsts.clear()

    def heal_topology(self) -> None:
        """Heal the TOPOLOGY layer only: clears dynamic events
        (degrades / zone partitions / flaps) on the attached topology;
        the base zone matrix and any nemesis-layer blocks stay."""
        if self.topology is not None:
            self.topology.heal_events()

    # -- transport surface ---------------------------------------------------

    async def call(self, dst: str, method: str, request: Any,
                   timeout_ms: Optional[float] = None) -> Any:
        if self.topology is not None:
            await self.topology.traverse(self.endpoint, dst, request,
                                         timeout_ms)
        if self.reorder_rate > 0 and self._rng.random() < self.reorder_rate:
            # hold THIS frame so later-submitted frames overtake it
            await asyncio.sleep(
                self._rng.uniform(0.0, self.reorder_max_delay_ms) / 1000.0)
        if self.delay_ms > 0:
            await asyncio.sleep(self.delay_ms / 1000.0)
        if dst in self._blocked_dsts or (
                self.drop_rate > 0 and self._rng.random() < self.drop_rate):
            # match the loopback's drop behavior (transport.py): a lost
            # request is only detected after a wait, so callers' timeout
            # and backoff machinery engages instead of hot-loop retrying
            wait_ms = min(timeout_ms, 50.0) if timeout_ms else 50.0
            await asyncio.sleep(wait_ms / 1000.0)
            raise RpcError(Status.error(
                RaftError.EHOSTDOWN, f"injected drop to {dst}"))
        if self.duplicate_rate > 0 \
                and self._rng.random() < self.duplicate_rate:
            # the wire delivered the frame twice: the receiver executes
            # both; we return the first response and drop the other's
            dup = asyncio.ensure_future(
                self._inner.call(dst, method, request, timeout_ms))
            dup.add_done_callback(
                lambda t: t.cancelled() or t.exception())
        return await self._inner.call(dst, method, request, timeout_ms)

    async def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            await close()
