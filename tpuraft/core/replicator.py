"""Replicator: per-(group, follower) log-shipping state machine.

Reference parity: ``core:core/Replicator`` + ``ReplicatorGroupImpl``
(SURVEY.md §3.1 north-star hot path, §4.2): probe → batched
AppendEntries → matchIndex advance → BallotBox#commitAt; separate
heartbeat cadence; InstallSnapshot fallback when the follower is behind
the compacted log; TimeoutNow for leadership transfer.

Round-4 redesign (SURVEY §3.5 "batched per-tick (group, peer) send
matrices", §8.2 "send-plans"): the replicator is a PASSIVE state
machine — no standing task, no per-RPC task, no log-manager waiter.
Events (log appends via :meth:`wake`, batch responses, engine masks)
drive :meth:`pump`, which builds up to a window of AppendEntries and
hands them to the shared per-endpoint :class:`~tpuraft.core.send_plane.
EndpointSender`; the whole window rides ONE ``multi_append`` RPC
together with every other group on the endpoint pair.  Standing tasks
per process drop from O(groups x peers) (the reference's
thread-per-replicator shape, and this file's own pre-r4 ``_run`` task)
to O(endpoints).

Pipelining (reference: inflight FIFO, ``maxReplicatorInflightMsgs``):
up to ``RaftOptions.max_inflight_msgs`` AppendEntries ride per batch,
resolved strictly in send order (the sender preserves order, the
receiver executes a node's items sequentially) — single-group
throughput is window x batch per endpoint round trip.  A head failure
rolls the window back to the confirmed ``match_index`` and re-probes,
exactly like the old FIFO.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from tpuraft.entity import PeerId, strip_entry_payload
from tpuraft.errors import RaftError
from tpuraft.rpc.messages import (
    AppendEntriesRequest,
    ErrorResponse,
    TimeoutNowRequest,
)
from tpuraft.rpc.transport import RpcError
from tpuraft.util.trace import TRACER as _TRACE
from tpuraft.util.trace import entry_ctx as trace_entry_ctx

LOG = logging.getLogger(__name__)


def _consume(t: "asyncio.Task") -> None:
    if not t.cancelled():
        t.exception()


# graftcheck: loop-confined — no lock: every field below is touched only
# on the owning node's event loop (wake/pump/response tasks)
class Replicator:
    def __init__(self, node, peer: PeerId):
        self._node = node
        self.peer = peer
        # ack stamps share the NODE's clock: quorum_ack_age_s compares
        # them against the same (possibly injected) timeline
        self._clock = node._clock
        self.next_index = node.log_manager.last_log_index() + 1
        self.match_index = 0
        self._matched = False  # True after the first successful probe/append
        self.last_rpc_ack = self._clock.monotonic()
        self._running = False
        self._hub = None  # HeartbeatHub when coalescing is enabled
        self._hb_task: Optional[asyncio.Task] = None
        # does the peer's endpoint serve multi_heartbeat?  Learned from
        # every AppendEntries response (probe/ack/beat); drives AUTO
        # coalescing (RaftOptions.coalesce_heartbeats=None)
        self.peer_multi_hb = False
        # quiesce handshake: EngineControl.maybe_quiesce arms this with
        # the lease horizon; the next hub pulse sends ONE quiesce beat
        # to this peer and clears it (0 = no handshake pending)
        self._quiesce_lease_ms = 0
        # set while this replicator lingers for a REMOVED peer (it keeps
        # shipping until the peer has the conf entry removing it, or a
        # timeout) — cleared if the peer is re-added meanwhile
        self.retiring = False
        self._transfer_target_index: Optional[int] = None
        self._catchup_waiters: list[tuple[int, asyncio.Future]] = []
        self.inflight_peak = 0  # high-water mark of the batch window
        # send-plane state
        self._sender = None          # EndpointSender (or None: direct mode)
        self._pending = False        # a batch is submitted / in flight
        self._inflight: list[tuple[int, int, int]] = []  # (prev, count, term)
        self._installing = False
        self._install_task: Optional[asyncio.Task] = None
        self._wake_scheduled = False
        self._delay_handle = None    # scheduled delayed pump (backoff)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._running = True
        node = self._node
        if node.node_manager is not None:
            if node.append_batcher is not None:
                # store-wide write plane: this group's windows join the
                # store's windowed per-destination append rounds
                # (AppendBatcher) instead of the send plane's
                # stop-and-wait endpoint lane — same submit/response
                # contract either way
                self._sender = node.append_batcher
            else:
                self._sender = node.node_manager.send_plane.sender(
                    self.peer.endpoint)
        else:
            self._sender = _DirectSender(self.peer.endpoint)
        self.wake()  # initial probe
        if getattr(node._ctrl, "drives_heartbeats", False):
            # engine control plane: the device tick's hb_due mask beats
            # this replicator (batched via HeartbeatHub.pulse) — no
            # per-replicator clock, no hub clock registration
            return
        hub = None
        opt = node.options.raft_options.coalesce_heartbeats
        if node.node_manager is not None and (
                opt is True or (opt is None and self.peer_multi_hb)):
            # auto mode joins the hub once the peer's capability is
            # known (probe responses advertise it; _note_peer_caps
            # migrates mid-leadership when it is learned later)
            hub = node.node_manager.heartbeat_hub
        self._hub = hub
        if hub is not None:
            hub.register(self)
        else:
            self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    def stop(self) -> None:
        self._running = False
        if self._hub is not None:
            self._hub.deregister(self)
            self._hub = None
        if self._hb_task:
            self._hb_task.cancel()
            self._hb_task = None
        if self._install_task:
            self._install_task.cancel()
            self._install_task = None
        if self._delay_handle is not None:
            self._delay_handle.cancel()
            self._delay_handle = None
        if isinstance(self._sender, _DirectSender):
            self._sender.stop()
        self._inflight.clear()
        for _, fut in self._catchup_waiters:
            if not fut.done():
                fut.set_result(False)
        self._catchup_waiters.clear()

    def wake(self) -> None:
        """Schedule a pump on the next loop pass (coalesces N wakes per
        pass into one batch build — e.g. a burst of appends)."""
        if self._wake_scheduled or not self._running:
            return
        self._wake_scheduled = True
        asyncio.get_running_loop().call_soon(self._wake_run)

    def _wake_run(self) -> None:
        self._wake_scheduled = False
        if self._running:
            self.pump()

    def _delayed_pump(self, delay_s: float) -> None:
        if not self._running or self._delay_handle is not None:
            return
        loop = asyncio.get_running_loop()

        def fire():
            self._delay_handle = None
            if self._running:
                self.pump()

        self._delay_handle = loop.call_later(delay_s, fire)

    # -- the send plan -------------------------------------------------------

    def pump(self) -> None:
        """Build the next send plan for this (group, peer) and submit it
        to the endpoint sender.  Synchronous: frames snapshot the term
        NOW (a step-down between build and send is caught by the
        receiver's term check + our term_at_send guard)."""
        node = self._node
        if (not self._running or not node.is_leader() or self._pending
                or self._installing):
            return
        lm = node.log_manager
        if self.next_index < lm.first_log_index():
            self._start_install()
            return
        if not self._matched:
            # EMPTY AppendEntries probe (reference: sendEmptyEntries):
            # discovers the follower's match point / backs off
            # next_index; data ships only once matched
            prev_index = self.next_index - 1
            prev_term = lm.get_term(prev_index)
            if prev_index > 0 and prev_term == 0 \
                    and prev_index >= lm.first_log_index():
                # prev entry gone (compacted concurrently)
                first = lm.first_log_index()
                self.next_index = first - 1 if first > 1 else 1
                self._start_install()
                return
            reqs = [self._build_request(prev_index, prev_term, [])]
            self._inflight = [(prev_index, 0, node.current_term)]
        else:
            ropts = node.options.raft_options
            window = max(1, ropts.max_inflight_msgs)
            reqs = []
            self._inflight = []
            next_index = self.next_index
            while (len(reqs) < window
                   and next_index <= lm.last_log_index()):
                prev_index = next_index - 1
                prev_term = lm.get_term(prev_index)
                if prev_index > 0 and prev_term == 0 \
                        and prev_index >= lm.first_log_index():
                    break  # prev compacted under us: probe/install next
                if prev_index < lm.first_log_index() - 1:
                    break  # behind the snapshot
                entries = lm.get_entries(next_index,
                                         ropts.max_entries_size,
                                         ropts.max_body_size)
                if not entries:
                    break
                if self._peer_is_witness():
                    # payload-stripped appends: the witness journals
                    # (index, term) only — a geo witness costs metadata
                    # bytes on the WAN, not the full log stream
                    stripped = [strip_entry_payload(e) for e in entries]
                    saved = sum(len(e.data) for e in entries)
                    if saved:
                        node.metrics.counter("witness-stripped-bytes",
                                             saved)
                    reqs.append(self._build_request(prev_index, prev_term,
                                                    stripped))
                else:
                    reqs.append(self._build_request(prev_index, prev_term,
                                                    entries))
                self._inflight.append((prev_index, len(entries),
                                       node.current_term))
                next_index += len(entries)
            if not reqs:
                if next_index < lm.first_log_index():
                    self._start_install()
                return  # idle: the next wake() re-pumps
            self.next_index = next_index  # optimistic, like the old FIFO
        if len(self._inflight) > self.inflight_peak:
            self.inflight_peak = len(self._inflight)
        self._pending = True
        self._sender.submit_append(self, reqs)

    def _peer_is_witness(self) -> bool:
        return self._node.peer_is_witness(self.peer)

    def _build_request(self, prev_index: int, prev_term: int,
                       entries: list) -> AppendEntriesRequest:
        node = self._node
        req = AppendEntriesRequest(
            group_id=node.group_id,
            server_id=str(node.server_id),
            peer_id=str(self.peer),
            term=node.current_term,
            prev_log_index=prev_index,
            prev_log_term=prev_term,
            committed_index=node.ballot_box.last_committed_index,
            entries=entries)
        if _TRACE.enabled and entries:
            # trailing trace contexts (b"" when no entry is traced):
            # follower-side append/flush spans join the leader's trace
            req.trace_ctx = trace_entry_ctx(entries)
        return req

    # -- batch resolution ----------------------------------------------------

    async def on_batch_responses(self, acks: list) -> None:
        """Resolve one submitted batch, strictly in send order (the old
        inflight-FIFO head loop, one whole window at a time).

        _pending stays True for the WHOLE resolution (cleared in the
        finally): this coroutine awaits mid-loop (step-down, transfer),
        and an external wake pumping a new batch against half-processed
        state would race the rollback paths."""
        inflight, self._inflight = self._inflight, []
        try:
            await self._resolve_batch(inflight, acks)
        finally:
            self._pending = False

    async def _resolve_batch(self, inflight: list, acks: list) -> None:
        node = self._node
        if not self._running:
            return
        eto_s = node.options.election_timeout_ms / 1000.0
        for (prev_index, count, term_at_send), ack in zip(inflight, acks):
            if node.current_term != term_at_send or not node.is_leader():
                self._rollback()
                return
            if isinstance(ack, (ErrorResponse, Exception)) or not hasattr(
                    ack, "success"):
                code = getattr(ack, "code", None)
                if code == int(RaftError.ENOENT):
                    # peer endpoint is up but doesn't host this node
                    # (removed / not yet started): silence, not a storm
                    self._rollback()
                    self._delayed_pump(eto_s / 2)
                else:
                    node.metrics.counter("replicate-error")
                    self._rollback()
                    self._delayed_pump(eto_s / 10)
                return
            self._note_peer_caps(ack)
            self.last_rpc_ack = self._clock.monotonic()
            node.on_peer_ack(self.peer, self.last_rpc_ack)
            if ack.term > node.current_term:
                self._rollback()
                await node.step_down_on_higher_term(
                    ack.term, f"append_entries response from {self.peer}")
                return
            if not ack.success:
                # log mismatch: back off using the follower's hints and
                # re-probe; conflict_index (first index of the
                # follower's conflicting term) skips a whole term run
                # per round trip (classic Raft §5.3 fast backoff)
                was_probe = count == 0 and not self._matched
                before = self.next_index
                self._rollback()
                self._matched = False
                candidates = [prev_index, ack.last_log_index + 1]
                if ack.conflict_index > 0:
                    candidates.append(ack.conflict_index)
                self.next_index = max(1, min(candidates))
                if was_probe and self.next_index == before:
                    # a follower that rejects everything: pace the probe
                    # loop instead of spinning at full speed
                    self._delayed_pump(eto_s / 20)
                else:
                    self.wake()
                return
            # success: follower's log matches through prev + entries
            # (reference: matchIndex = prevLogIndex + entriesCount)
            self._matched = True
            new_match = prev_index + count
            if new_match > self.match_index:
                self.match_index = new_match
                node.on_match_advanced(self.peer, self.match_index)
                self._check_catchup()
            if count:
                node.metrics.counter("replicate-entries-count", count)
        await self._maybe_timeout_now()
        self.wake()  # more entries may have queued while we were out

    async def on_batch_error(self) -> None:
        """The whole batch RPC failed (endpoint unreachable/timeout)."""
        node = self._node
        self._pending = False
        self._rollback()
        if not self._running or not node.is_leader():
            return
        node.metrics.counter("replicate-error")
        self._delayed_pump(node.options.election_timeout_ms / 1000.0 / 10)

    def _rollback(self) -> None:
        """Drop optimistic sends: return next_index to just past the
        last CONFIRMED match."""
        self._inflight = []
        if self._matched:
            self.next_index = max(self.match_index + 1, 1)

    # -- snapshot install ----------------------------------------------------

    def _start_install(self) -> None:
        if self._installing or not self._running:
            return
        self._installing = True

        async def run():
            node = self._node
            try:
                ok = await node.install_snapshot_on(self.peer, self)
                if not ok:
                    await asyncio.sleep(
                        node.options.election_timeout_ms / 1000.0 / 2)
            except asyncio.CancelledError:
                raise
            except Exception:
                LOG.exception("snapshot install to %s failed", self.peer)
            finally:
                self._installing = False
                self._install_task = None
                self.wake()

        self._install_task = asyncio.ensure_future(run())
        self._install_task.add_done_callback(_consume)

    # -- heartbeats ----------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        node = self._node
        interval = (node.options.election_timeout_ms
                    / node.options.raft_options.election_heartbeat_factor / 1000.0)
        try:
            while self._running and node.is_leader():
                await asyncio.sleep(interval)
                await self.send_heartbeat()
        except asyncio.CancelledError:
            return

    def build_heartbeat_request(self) -> AppendEntriesRequest:
        """The empty AppendEntries beat for this (group, peer) — shared
        by the direct path and the coalescing HeartbeatHub."""
        node = self._node
        lm = node.log_manager
        prev_index = min(self.match_index, lm.last_log_index())
        return AppendEntriesRequest(
            group_id=node.group_id,
            server_id=str(node.server_id),
            peer_id=str(self.peer),
            term=node.current_term,
            prev_log_index=prev_index,
            prev_log_term=lm.get_term(prev_index),
            committed_index=min(node.ballot_box.last_committed_index,
                                prev_index),
            entries=[],
        )

    def _note_peer_caps(self, resp) -> None:
        """Track the peer endpoint's multi_heartbeat capability; in AUTO
        mode (coalesce_heartbeats=None) migrate this replicator's beat
        source between the direct loop and the hub to match it."""
        mh = bool(getattr(resp, "multi_hb", False))
        if mh == self.peer_multi_hb:
            return
        self.peer_multi_hb = mh
        node = self._node
        if (not self._running
                or getattr(node._ctrl, "drives_heartbeats", False)
                or node.options.raft_options.coalesce_heartbeats is not None
                or node.node_manager is None):
            return  # engine beats handle this per-tick; or mode is fixed
        if mh and self._hub is None:
            if self._hb_task is not None:
                self._hb_task.cancel()
                self._hb_task = None
            self._hub = node.node_manager.heartbeat_hub
            self._hub.register(self)
        elif not mh and self._hub is not None:
            self._hub.deregister(self)
            self._hub = None
            self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    async def process_heartbeat_response(self, resp) -> bool:
        """Ack bookkeeping shared by both heartbeat paths: lease acks,
        step-down on higher term, re-probe on lost match."""
        node = self._node
        if resp.term > node.current_term:
            await node.step_down_on_higher_term(
                resp.term, f"heartbeat response from {self.peer}")
            return False
        self.last_rpc_ack = self._clock.monotonic()
        node.on_peer_ack(self.peer, self.last_rpc_ack)
        if not resp.success and self._matched:
            # follower's log no longer matches (e.g. restarted): re-probe
            self._matched = False
            self.next_index = min(self.next_index, resp.last_log_index + 1) or 1
            self.wake()
        # LAST, with no awaits after: an AUTO-mode migration may cancel
        # the very _hb_task running this coroutine, and a pending
        # CancelledError would abort any later await (observed hazard:
        # swallowing a mandated step-down)
        self._note_peer_caps(resp)
        return True

    async def send_heartbeat(self) -> bool:
        """One empty AppendEntries; returns True on in-term ack.
        Also the quorum-confirmation primitive for ReadIndex (SAFE)."""
        node = self._node
        if not node.is_leader():
            return False
        req = self.build_heartbeat_request()
        t0 = self._clock.monotonic()
        try:
            resp = await node.transport.append_entries(
                self.peer.endpoint, req,
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError:
            return False
        health = node.options.health
        if health is not None:
            # gray-failure signal: the beat's RTT scores the PEER's
            # endpoint — a limping follower shows up here long before
            # it goes silent
            health.note_peer_rtt(self.peer.endpoint,
                                 self._clock.monotonic() - t0)
        return await self.process_heartbeat_response(resp)

    # -- catch-up (membership change) ----------------------------------------

    def wait_matched(self, target: int, timeout_s: float) -> asyncio.Future:
        """Resolves True when match_index reaches ``target``, False on
        timeout or replicator stop."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if self.match_index >= target:
            fut.set_result(True)
            return fut
        self._catchup_waiters.append((target, fut))

        def _timeout():
            if not fut.done():
                fut.set_result(False)

        handle = loop.call_later(timeout_s, _timeout)
        fut.add_done_callback(lambda _f: handle.cancel())
        return fut

    def wait_caught_up(self, margin: int, timeout_s: float) -> asyncio.Future:
        """Resolves True when match_index is within ``margin`` of the log
        tail (reference: Replicator#waitForCaughtUp driving CATCHING_UP)."""
        target = max(1, self._node.log_manager.last_log_index() - margin)
        return self.wait_matched(target, timeout_s)

    def _check_catchup(self) -> None:
        rest = []
        for target, fut in self._catchup_waiters:
            if fut.done():
                continue
            if self.match_index >= target:
                fut.set_result(True)
            else:
                rest.append((target, fut))
        self._catchup_waiters = rest

    # -- leadership transfer -------------------------------------------------

    def transfer_leadership(self, log_index: int) -> None:
        """Send TimeoutNow once this peer's match reaches log_index."""
        self._transfer_target_index = log_index
        if self.match_index >= log_index:
            t = asyncio.ensure_future(self._maybe_timeout_now())
            t.add_done_callback(_consume)
        else:
            self.wake()

    def stop_transfer_leadership(self) -> None:
        """Cancel a pending TimeoutNow trigger (reference:
        Replicator#stopTransferLeadership).  Called when the transfer
        watchdog resumes leadership: without this, a partitioned target
        catching up MUCH later would still receive TimeoutNow and depose
        a leader that long since moved on."""
        self._transfer_target_index = None

    async def _maybe_timeout_now(self) -> None:
        if (self._transfer_target_index is not None
                and self.match_index >= self._transfer_target_index):
            self._transfer_target_index = None
            node = self._node
            req = TimeoutNowRequest(
                group_id=node.group_id,
                server_id=str(node.server_id),
                peer_id=str(self.peer),
                term=node.current_term,
            )
            try:
                await node.transport.timeout_now(self.peer.endpoint, req)
            except RpcError:
                LOG.warning("timeout_now to %s failed", self.peer)


class _DirectSender:
    """Degenerate per-(group, peer) sender for nodes WITHOUT a
    NodeManager (bare unit-test nodes): same submit/response contract as
    EndpointSender, but ships each frame as its own append_entries RPC
    from one transient task per batch."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._task: Optional[asyncio.Task] = None

    def submit_append(self, rep: Replicator, reqs: list) -> None:
        from tpuraft.core.send_plane import sequential_appends

        self._task = asyncio.ensure_future(
            sequential_appends(rep, self.endpoint, reqs, timed=True))
        self._task.add_done_callback(_consume)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# graftcheck: loop-confined
class ReplicatorGroup:
    """All replicators of one leader node (reference: ReplicatorGroupImpl)."""

    def __init__(self, node):
        self._node = node
        self._replicators: dict[PeerId, Replicator] = {}

    def add(self, peer: PeerId) -> Replicator:
        r = self._replicators.get(peer)
        if r is not None:
            if not r.retiring:
                return r
            # re-added while lingering for its REMOVAL: the old
            # replicator's match_index may predate a storage wipe —
            # start fresh so the peer re-earns its match from a probe
            # instead of instantly "passing" catch-up with a stale high
            # watermark
            self.remove(peer)
        r = Replicator(self._node, peer)
        self._replicators[peer] = r
        r.start()
        return r

    def remove(self, peer: PeerId) -> None:
        r = self._replicators.pop(peer, None)
        if r:
            r.stop()

    def retire(self, peer: PeerId, min_match_index: int,
               timeout_s: float) -> None:
        """Linger a REMOVED peer's replicator until the peer has received
        the log through ``min_match_index`` (the conf entry that removed
        it — so it steps out instead of starting disruptive elections),
        then stop it.  Bounded by ``timeout_s`` for dead/partitioned
        peers.  A concurrent re-add (membership flap) cancels the
        retirement; a step-down's stop_all wins over it."""
        r = self._replicators.get(peer)
        if r is None:
            return
        r.retiring = True
        if r.match_index >= min_match_index:
            self.remove(peer)
            return
        fut = r.wait_matched(min_match_index, timeout_s)

        def _done(_f):
            if r.retiring and self._replicators.get(peer) is r:
                self.remove(peer)

        fut.add_done_callback(_done)

    def get(self, peer: PeerId) -> Optional[Replicator]:
        return self._replicators.get(peer)

    def stop_all(self) -> None:
        for r in self._replicators.values():
            r.stop()
        self._replicators.clear()

    def progress(self) -> list[tuple[PeerId, int, bool]]:
        """Public snapshot of (peer, next_index, matched) for observability
        (Node#describe, CLI)."""
        return sorted(((p, r.next_index, r._matched)
                       for p, r in self._replicators.items()),
                      key=lambda row: str(row[0]))

    def wake_all(self) -> None:
        for r in self._replicators.values():
            r.wake()

    def peers(self) -> list[PeerId]:
        return list(self._replicators)

    def all(self) -> list[Replicator]:
        return list(self._replicators.values())

    async def heartbeat_round(self) -> int:
        """Concurrent heartbeat to all peers; returns ack count (for SAFE
        ReadIndex quorum confirmation)."""
        if not self._replicators:
            return 0
        results = await asyncio.gather(
            *(r.send_heartbeat() for r in self._replicators.values()),
            return_exceptions=True)
        return sum(1 for x in results if x is True)
