"""Replicator: per-(group, follower) log shipping state machine.

Reference parity: ``core:core/Replicator`` + ``ReplicatorGroupImpl``
(SURVEY.md §3.1 north-star hot path, §4.2): probe → batched AppendEntries
→ matchIndex advance → BallotBox#commitAt; separate heartbeat cadence;
InstallSnapshot fallback when the follower is behind the compacted log;
TimeoutNow for leadership transfer.

Pipelining (reference: inflight FIFO, ``maxReplicatorInflightMsgs``):
up to ``RaftOptions.max_inflight_msgs`` AppendEntries ride per peer,
resolved strictly in send order against the follower's per-(group,
leader) ordered execution lane (NodeManager) — single-group throughput
is batch*window per RTT instead of batch per RTT.  The asyncio loop
additionally pipelines across groups/peers, and the multi-raft engine
batches G x P quorum math per device tick.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Optional

from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.rpc.messages import (
    AppendEntriesRequest,
    AppendEntriesResponse,
    TimeoutNowRequest,
)
from tpuraft.rpc.transport import RpcError

LOG = logging.getLogger(__name__)


def _drop_task(t: "asyncio.Task") -> None:
    """Cancel an in-flight RPC task and make sure a failure that
    already completed is retrieved (else asyncio logs 'Task exception
    was never retrieved' per dropped send during any outage)."""
    t.cancel()

    def _swallow(tt):
        if not tt.cancelled():
            tt.exception()

    t.add_done_callback(_swallow)


class Replicator:
    def __init__(self, node, peer: PeerId):
        self._node = node
        self.peer = peer
        self.next_index = node.log_manager.last_log_index() + 1
        self.match_index = 0
        self._matched = False  # True after the first successful probe/append
        self.last_rpc_ack = time.monotonic()
        self._running = False
        self._task: Optional[asyncio.Task] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._hub = None  # HeartbeatHub when coalescing is enabled
        # does the peer's endpoint serve multi_heartbeat?  Learned from
        # every AppendEntries response (probe/ack/beat); drives AUTO
        # coalescing (RaftOptions.coalesce_heartbeats=None)
        self.peer_multi_hb = False
        self._transfer_target_index: Optional[int] = None
        self._catchup_waiters: list[tuple[int, asyncio.Future]] = []
        self.inflight_peak = 0  # high-water mark of the pipeline window

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._task = asyncio.ensure_future(self._run())
        node = self._node
        if getattr(node._ctrl, "drives_heartbeats", False):
            # engine control plane: the device tick's hb_due mask beats
            # this replicator (batched via HeartbeatHub.pulse) — no
            # per-replicator clock, no hub clock registration
            return
        hub = None
        opt = node.options.raft_options.coalesce_heartbeats
        if node.node_manager is not None and (
                opt is True or (opt is None and self.peer_multi_hb)):
            # auto mode joins the hub once the peer's capability is
            # known (probe responses advertise it; _note_peer_caps
            # migrates mid-leadership when it is learned later)
            hub = node.node_manager.heartbeat_hub
        self._hub = hub
        if hub is not None:
            hub.register(self)
        else:
            self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    def stop(self) -> None:
        self._running = False
        if self._hub is not None:
            self._hub.deregister(self)
            self._hub = None
        for t in (self._task, self._hb_task):
            if t:
                t.cancel()
        self._task = self._hb_task = None
        for _, fut in self._catchup_waiters:
            if not fut.done():
                fut.set_result(False)
        self._catchup_waiters.clear()

    def wake(self) -> None:
        self._wake.set()

    # -- main replication loop ----------------------------------------------

    async def _run(self) -> None:
        try:
            while self._running and self._node.is_leader():
                lm = self._node.log_manager
                if self.next_index < lm.first_log_index():
                    ok = await self._install_snapshot()
                    if not ok:
                        await asyncio.sleep(
                            self._node.options.election_timeout_ms / 1000.0 / 2)
                    continue
                if not self._matched:
                    # probe first (reference: sendEmptyEntries on start):
                    # discovers the follower's log tail / backs off next_index
                    await self._send_entries()
                    continue
                if self.next_index > lm.last_log_index():
                    # nothing to send: wait for new entries (or stop)
                    self._wake.clear()
                    waiter = lm.wait_for(self.next_index)
                    wake = asyncio.ensure_future(self._wake.wait())
                    try:
                        await asyncio.wait(
                            [waiter, wake],
                            return_when=asyncio.FIRST_COMPLETED)
                    finally:
                        # also on cancellation, or the Event.wait task
                        # outlives the replicator ("destroyed pending")
                        waiter.cancel()
                        wake.cancel()
                    continue
                await self._pipeline_entries()
        except asyncio.CancelledError:
            return
        except Exception:
            LOG.exception("replicator %s crashed", self.peer)

    async def _pipeline_entries(self) -> None:
        """Windowed pipelined replication (reference: the Replicator
        inflight FIFO, ``maxReplicatorInflightMsgs``): keep up to W
        AppendEntries RPCs in flight, advancing ``next_index``
        optimistically as batches ship.  Responses resolve strictly in
        send order — the head of the FIFO is awaited, so out-of-order
        completions just wait their turn.  Any head failure rolls the
        window back to the confirmed ``match_index`` and re-probes.
        The follower executes in arrival order (NodeManager's
        per-(group, leader) lanes), so in-window requests cannot race
        each other to the log."""
        node = self._node
        lm = node.log_manager
        ropts = node.options.raft_options
        window = max(1, ropts.max_inflight_msgs)
        inflight: deque = deque()
        try:
            while self._running and node.is_leader() and self._matched:
                compacted = False
                while (len(inflight) < window
                       and self.next_index <= lm.last_log_index()):
                    prev_index = self.next_index - 1
                    prev_term = lm.get_term(prev_index)
                    if prev_index > 0 and prev_term == 0 \
                            and prev_index >= lm.first_log_index():
                        compacted = True   # prev gone under us
                        break
                    if prev_index < lm.first_log_index() - 1:
                        compacted = True   # behind the snapshot
                        break
                    entries = lm.get_entries(self.next_index,
                                             ropts.max_entries_size,
                                             ropts.max_body_size)
                    if not entries:
                        break
                    req = AppendEntriesRequest(
                        group_id=node.group_id,
                        server_id=str(node.server_id),
                        peer_id=str(self.peer),
                        term=node.current_term,
                        prev_log_index=prev_index,
                        prev_log_term=prev_term,
                        committed_index=node.ballot_box.last_committed_index,
                        entries=entries)
                    task = asyncio.ensure_future(
                        node.transport.append_entries(
                            self.peer.endpoint, req,
                            timeout_ms=node.options.election_timeout_ms))
                    inflight.append((prev_index, len(entries),
                                     node.current_term, task))
                    self.next_index += len(entries)
                if len(inflight) > self.inflight_peak:
                    self.inflight_peak = len(inflight)
                if not inflight:
                    if compacted:
                        # route to the install path (same as the serial
                        # probe did) instead of hard-spinning the outer
                        # loop against a compacted log
                        first = lm.first_log_index()
                        self.next_index = first - 1 if first > 1 else 1
                    return          # outer loop waits / installs
                prev_index, count, term_at_send, task = inflight.popleft()
                try:
                    with node.metrics.timer("replicate-entries"):
                        resp = await task
                except RpcError:
                    node.metrics.counter("replicate-error")
                    self._roll_back_window(inflight)
                    await asyncio.sleep(
                        node.options.election_timeout_ms / 1000.0 / 10)
                    return
                if not self._running or node.current_term != term_at_send:
                    self._roll_back_window(inflight)
                    return
                self._note_peer_caps(resp)
                self.last_rpc_ack = time.monotonic()
                node.on_peer_ack(self.peer, self.last_rpc_ack)
                if resp.term > node.current_term:
                    self._roll_back_window(inflight)
                    await node.step_down_on_higher_term(
                        resp.term,
                        f"append_entries response from {self.peer}")
                    return
                if not resp.success:
                    # conflict: back off with the follower's hints and
                    # re-probe (same formula as the serial path)
                    self._roll_back_window(inflight)
                    self._matched = False
                    candidates = [prev_index, resp.last_log_index + 1]
                    if resp.conflict_index > 0:
                        candidates.append(resp.conflict_index)
                    self.next_index = max(1, min(candidates))
                    return
                new_match = prev_index + count
                if new_match > self.match_index:
                    self.match_index = new_match
                    node.on_match_advanced(self.peer, self.match_index)
                    self._check_catchup()
                node.metrics.counter("replicate-entries-count", count)
                await self._maybe_timeout_now()
        finally:
            # never leak in-flight RPC tasks (stop / cancellation paths);
            # next_index is rolled back by the exits that need it
            for *_, t in inflight:
                _drop_task(t)
            inflight.clear()

    def _roll_back_window(self, inflight) -> None:
        """Drop optimistic sends: cancel queued RPCs and return
        next_index to just past the last CONFIRMED match."""
        for *_, t in inflight:
            _drop_task(t)
        inflight.clear()
        self.next_index = max(self.match_index + 1, 1)

    async def _send_entries(self) -> None:
        node = self._node
        lm = node.log_manager
        prev_index = self.next_index - 1
        prev_term = lm.get_term(prev_index)
        if prev_index > 0 and prev_term == 0 and prev_index >= lm.first_log_index():
            # prev entry gone (compacted concurrently) — snapshot path next loop
            self.next_index = lm.first_log_index() - 1 if lm.first_log_index() > 1 else 1
            return
        # EMPTY AppendEntries probe (reference: sendEmptyEntries):
        # discovers the follower's match point / backs off next_index;
        # data shipping happens exclusively in _pipeline_entries once
        # matched
        entries = []
        req = AppendEntriesRequest(
            group_id=node.group_id,
            server_id=str(node.server_id),
            peer_id=str(self.peer),
            term=node.current_term,
            prev_log_index=prev_index,
            prev_log_term=prev_term,
            committed_index=node.ballot_box.last_committed_index,
            entries=entries,
        )
        term_at_send = node.current_term
        try:
            with node.metrics.timer("replicate-entries"):
                resp: AppendEntriesResponse = await node.transport.append_entries(
                    self.peer.endpoint, req,
                    timeout_ms=node.options.election_timeout_ms)
        except RpcError:
            node.metrics.counter("replicate-error")
            await asyncio.sleep(node.options.election_timeout_ms / 1000.0 / 10)
            return
        if not self._running or node.current_term != term_at_send:
            return
        self._note_peer_caps(resp)
        self.last_rpc_ack = time.monotonic()
        node.on_peer_ack(self.peer, self.last_rpc_ack)
        if resp.term > node.current_term:
            await node.step_down_on_higher_term(
                resp.term, f"append_entries response from {self.peer}")
            return
        if not resp.success:
            # log mismatch: back off using the follower's hints, re-probe.
            # conflict_index (first index of the follower's conflicting
            # term) skips a whole term run per round trip.
            self._matched = False
            before = self.next_index
            candidates = [self.next_index - 1, resp.last_log_index + 1]
            if resp.conflict_index > 0:
                candidates.append(resp.conflict_index)
            self.next_index = max(1, min(candidates))
            if self.next_index == before:
                # no progress (e.g. a follower that rejects everything):
                # pace the probe loop instead of spinning at full speed
                await asyncio.sleep(
                    node.options.election_timeout_ms / 1000.0 / 20)
            return
        # success: follower's log matches through prev
        # (reference: matchIndex = request.prevLogIndex + entriesCount)
        self._matched = True
        new_match = prev_index
        if new_match > self.match_index:
            self.match_index = new_match
            node.on_match_advanced(self.peer, self.match_index)
            self._check_catchup()
        self.next_index = max(self.next_index, new_match + 1)
        await self._maybe_timeout_now()

    # -- heartbeats ----------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        node = self._node
        interval = (node.options.election_timeout_ms
                    / node.options.raft_options.election_heartbeat_factor / 1000.0)
        try:
            while self._running and node.is_leader():
                await asyncio.sleep(interval)
                await self.send_heartbeat()
        except asyncio.CancelledError:
            return

    def build_heartbeat_request(self) -> AppendEntriesRequest:
        """The empty AppendEntries beat for this (group, peer) — shared
        by the direct path and the coalescing HeartbeatHub."""
        node = self._node
        lm = node.log_manager
        prev_index = min(self.match_index, lm.last_log_index())
        return AppendEntriesRequest(
            group_id=node.group_id,
            server_id=str(node.server_id),
            peer_id=str(self.peer),
            term=node.current_term,
            prev_log_index=prev_index,
            prev_log_term=lm.get_term(prev_index),
            committed_index=min(node.ballot_box.last_committed_index,
                                prev_index),
            entries=[],
        )

    def _note_peer_caps(self, resp) -> None:
        """Track the peer endpoint's multi_heartbeat capability; in AUTO
        mode (coalesce_heartbeats=None) migrate this replicator's beat
        source between the direct loop and the hub to match it."""
        mh = bool(getattr(resp, "multi_hb", False))
        if mh == self.peer_multi_hb:
            return
        self.peer_multi_hb = mh
        node = self._node
        if (not self._running
                or getattr(node._ctrl, "drives_heartbeats", False)
                or node.options.raft_options.coalesce_heartbeats is not None
                or node.node_manager is None):
            return  # engine beats handle this per-tick; or mode is fixed
        if mh and self._hub is None:
            if self._hb_task is not None:
                self._hb_task.cancel()
                self._hb_task = None
            self._hub = node.node_manager.heartbeat_hub
            self._hub.register(self)
        elif not mh and self._hub is not None:
            self._hub.deregister(self)
            self._hub = None
            self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    async def process_heartbeat_response(self, resp) -> bool:
        """Ack bookkeeping shared by both heartbeat paths: lease acks,
        step-down on higher term, re-probe on lost match."""
        node = self._node
        if resp.term > node.current_term:
            await node.step_down_on_higher_term(
                resp.term, f"heartbeat response from {self.peer}")
            return False
        self.last_rpc_ack = time.monotonic()
        node.on_peer_ack(self.peer, self.last_rpc_ack)
        if not resp.success and self._matched:
            # follower's log no longer matches (e.g. restarted): re-probe
            self._matched = False
            self.next_index = min(self.next_index, resp.last_log_index + 1) or 1
            self.wake()
        # LAST, with no awaits after: an AUTO-mode migration may cancel
        # the very _hb_task running this coroutine, and a pending
        # CancelledError would abort any later await (observed hazard:
        # swallowing a mandated step-down)
        self._note_peer_caps(resp)
        return True

    async def send_heartbeat(self) -> bool:
        """One empty AppendEntries; returns True on in-term ack.
        Also the quorum-confirmation primitive for ReadIndex (SAFE)."""
        node = self._node
        if not node.is_leader():
            return False
        req = self.build_heartbeat_request()
        try:
            resp = await node.transport.append_entries(
                self.peer.endpoint, req,
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError:
            return False
        return await self.process_heartbeat_response(resp)

    # -- catch-up (membership change) ----------------------------------------

    def wait_caught_up(self, margin: int, timeout_s: float) -> asyncio.Future:
        """Resolves True when match_index is within ``margin`` of the log
        tail (reference: Replicator#waitForCaughtUp driving CATCHING_UP)."""
        fut = asyncio.get_running_loop().create_future()
        target = max(1, self._node.log_manager.last_log_index() - margin)
        if self.match_index >= target:
            fut.set_result(True)
            return fut
        self._catchup_waiters.append((target, fut))

        def _timeout():
            if not fut.done():
                fut.set_result(False)

        asyncio.get_running_loop().call_later(timeout_s, _timeout)
        return fut

    def _check_catchup(self) -> None:
        rest = []
        for target, fut in self._catchup_waiters:
            if fut.done():
                continue
            if self.match_index >= target:
                fut.set_result(True)
            else:
                rest.append((target, fut))
        self._catchup_waiters = rest

    # -- leadership transfer -------------------------------------------------

    def transfer_leadership(self, log_index: int) -> None:
        """Send TimeoutNow once this peer's match reaches log_index."""
        self._transfer_target_index = log_index
        if self.match_index >= log_index:
            asyncio.ensure_future(self._maybe_timeout_now())
        else:
            self.wake()

    async def _maybe_timeout_now(self) -> None:
        if (self._transfer_target_index is not None
                and self.match_index >= self._transfer_target_index):
            self._transfer_target_index = None
            node = self._node
            req = TimeoutNowRequest(
                group_id=node.group_id,
                server_id=str(node.server_id),
                peer_id=str(self.peer),
                term=node.current_term,
            )
            try:
                await node.transport.timeout_now(self.peer.endpoint, req)
            except RpcError:
                LOG.warning("timeout_now to %s failed", self.peer)

    # -- snapshot install ----------------------------------------------------

    async def _install_snapshot(self) -> bool:
        return await self._node.install_snapshot_on(self.peer, self)


class ReplicatorGroup:
    """All replicators of one leader node (reference: ReplicatorGroupImpl)."""

    def __init__(self, node):
        self._node = node
        self._replicators: dict[PeerId, Replicator] = {}

    def add(self, peer: PeerId) -> Replicator:
        if peer in self._replicators:
            return self._replicators[peer]
        r = Replicator(self._node, peer)
        self._replicators[peer] = r
        r.start()
        return r

    def remove(self, peer: PeerId) -> None:
        r = self._replicators.pop(peer, None)
        if r:
            r.stop()

    def get(self, peer: PeerId) -> Optional[Replicator]:
        return self._replicators.get(peer)

    def stop_all(self) -> None:
        for r in self._replicators.values():
            r.stop()
        self._replicators.clear()

    def progress(self) -> list[tuple[PeerId, int, bool]]:
        """Public snapshot of (peer, next_index, matched) for observability
        (Node#describe, CLI)."""
        return sorted(((p, r.next_index, r._matched)
                       for p, r in self._replicators.items()),
                      key=lambda row: str(row[0]))

    def wake_all(self) -> None:
        for r in self._replicators.values():
            r.wake()

    def peers(self) -> list[PeerId]:
        return list(self._replicators)

    def all(self) -> list[Replicator]:
        return list(self._replicators.values())

    async def heartbeat_round(self) -> int:
        """Concurrent heartbeat to all peers; returns ack count (for SAFE
        ReadIndex quorum confirmation)."""
        if not self._replicators:
            return 0
        results = await asyncio.gather(
            *(r.send_heartbeat() for r in self._replicators.values()),
            return_exceptions=True)
        return sum(1 for x in results if x is True)
