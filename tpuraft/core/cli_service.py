"""CLI (admin) service: server-side processors + client-side CliService.

Reference parity (SURVEY.md §3.1 "CLI service & processors"):
server side = ``core:rpc/impl/cli/*RequestProcessor`` (one per admin op,
all extending ``BaseCliRequestProcessor`` which resolves groupId→Node and
rejects non-leaders); client side = ``core:core/CliServiceImpl`` +
``core:rpc/impl/cli/CliClientServiceImpl`` — each op locates the group
leader (refreshing on redirect), issues the RPC, retries boundedly.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from tpuraft.conf import Configuration
from tpuraft.core.node import Node
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.options import CliOptions
from tpuraft.rpc.cli_messages import (
    AddLearnersRequest,
    AddPeerRequest,
    ChangePeersRequest,
    CliResponse,
    DescribeMetricsRequest,
    GetLeaderRequest,
    GetLeaderResponse,
    GetPeersRequest,
    GetPeersResponse,
    RemoveLearnersRequest,
    RemovePeerRequest,
    ResetLearnersRequest,
    ResetPeersRequest,
    SnapshotRequest,
    TransferLeaderRequest,
)
from tpuraft.rpc.transport import RpcError

LOG = logging.getLogger(__name__)


# ---- server side -----------------------------------------------------------


class CliProcessors:
    """Registers one handler per admin op on the shared RpcServer.

    Reference: BaseCliRequestProcessor subclasses bound by
    ``RaftRpcServerFactory#addRaftRequestProcessors``.
    """

    def __init__(self, node_manager: NodeManager):
        self._nm = node_manager
        s = node_manager.server
        s.register("cli_get_leader", self._get_leader)
        s.register("cli_get_peers", self._get_peers)
        s.register("cli_add_peer", self._add_peer)
        s.register("cli_remove_peer", self._remove_peer)
        s.register("cli_change_peers", self._change_peers)
        s.register("cli_reset_peers", self._reset_peers)
        s.register("cli_snapshot", self._snapshot)
        s.register("cli_transfer_leader", self._transfer_leader)
        s.register("cli_add_learners", self._add_learners)
        s.register("cli_remove_learners", self._remove_learners)
        s.register("cli_reset_learners", self._reset_learners)

    def _find(self, group_id: str, peer_id: str) -> Optional[Node]:
        if peer_id:
            return self._nm.get(group_id, peer_id)
        for n in self._nm.list_nodes():
            if n.group_id == group_id:
                return n
        return None

    def _leader_node(self, req) -> tuple[Optional[Node], Optional[CliResponse]]:
        node = self._find(req.group_id, req.peer_id)
        if node is None:
            return None, CliResponse(
                code=int(RaftError.ENOENT),
                msg=f"no node for group {req.group_id} here")
        if not node.is_leader():
            leader = node.get_leader_id()
            return None, CliResponse(
                code=int(RaftError.EPERM),
                msg=f"not leader; leader={leader if leader else '?'}")
        return node, None

    @staticmethod
    def _from_status(st: Status, node: Optional[Node] = None) -> CliResponse:
        resp = CliResponse(code=st.code, msg=st.error_msg)
        if node is not None:
            resp.new_peers = [str(p) for p in node.list_peers()]
        return resp

    async def _get_leader(self, req: GetLeaderRequest) -> GetLeaderResponse:
        node = self._find(req.group_id, req.peer_id)
        if node is None:
            return GetLeaderResponse(leader_id="", success=False)
        leader = node.get_leader_id()
        return GetLeaderResponse(
            leader_id=str(leader) if leader and not leader.is_empty() else "",
            success=bool(leader) and not leader.is_empty())

    async def _get_peers(self, req: GetPeersRequest) -> GetPeersResponse:
        # membership queries must come from the leader — a deposed node
        # would answer with a stale (or, for only_alive, empty) view
        # (reference: GetPeersRequestProcessor requires leadership)
        node = self._find(req.group_id, req.peer_id)
        if node is None or not node.is_leader():
            return GetPeersResponse(success=False)
        peers = (node.list_alive_peers() if req.only_alive
                 else node.list_peers())
        return GetPeersResponse(
            peers=[str(p) for p in peers],
            learners=[str(p) for p in node.list_learners()],
            witnesses=[str(p) for p in node.conf_entry.conf.witnesses])

    async def _add_peer(self, req: AddPeerRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        old = [str(p) for p in node.list_peers()]
        st = await node.add_peer(PeerId.parse(req.adding),
                                 witness=bool(getattr(req, "witness", False)))
        resp = self._from_status(st, node)
        resp.old_peers = old
        return resp

    async def _remove_peer(self, req: RemovePeerRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        old = [str(p) for p in node.list_peers()]
        st = await node.remove_peer(PeerId.parse(req.removing))
        resp = self._from_status(st, node)
        resp.old_peers = old
        return resp

    async def _change_peers(self, req: ChangePeersRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        old = [str(p) for p in node.list_peers()]
        conf = Configuration([PeerId.parse(p) for p in req.new_peers],
                             [PeerId.parse(p) for p in req.new_learners],
                             [PeerId.parse(p) for p in req.new_witnesses])
        st = await node.change_peers(conf)
        resp = self._from_status(st, node)
        resp.old_peers = old
        return resp

    async def _reset_peers(self, req: ResetPeersRequest) -> CliResponse:
        # resetPeers is a last-resort op allowed on non-leaders (reference:
        # ResetPeerRequestProcessor does not require leadership).
        node = self._find(req.group_id, req.peer_id)
        if node is None:
            return CliResponse(code=int(RaftError.ENOENT),
                               msg=f"no node for group {req.group_id} here")
        conf = Configuration([PeerId.parse(p) for p in req.new_peers],
                             [PeerId.parse(p) for p in req.new_learners],
                             [PeerId.parse(p) for p in req.new_witnesses])
        st = await node.reset_peers(conf)
        return self._from_status(st, node)

    async def _snapshot(self, req: SnapshotRequest) -> CliResponse:
        node = self._find(req.group_id, req.peer_id)
        if node is None:
            return CliResponse(code=int(RaftError.ENOENT),
                               msg=f"no node for group {req.group_id} here")
        st = await node.snapshot()
        return self._from_status(st)

    async def _transfer_leader(self, req: TransferLeaderRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        st = await node.transfer_leadership_to(PeerId.parse(req.transferee))
        return self._from_status(st, node)

    async def _add_learners(self, req: AddLearnersRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        st = await node.add_learners([PeerId.parse(p) for p in req.learners])
        return self._from_status(st, node)

    async def _remove_learners(self, req: RemoveLearnersRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        st = await node.remove_learners([PeerId.parse(p) for p in req.learners])
        return self._from_status(st, node)

    async def _reset_learners(self, req: ResetLearnersRequest) -> CliResponse:
        node, err = self._leader_node(req)
        if err:
            return err
        st = await node.reset_learners([PeerId.parse(p) for p in req.learners])
        return self._from_status(st, node)


# ---- client side -----------------------------------------------------------


class CliService:
    """Admin client: locates the leader, issues the op, retries on redirect.

    Reference: ``core:core/CliServiceImpl`` (ops) over
    ``CliClientServiceImpl`` (RPC + connection mgmt).  ``transport`` is any
    object with ``call(dst_endpoint, method, request, timeout_ms)``.
    """

    def __init__(self, transport, options: Optional[CliOptions] = None):
        self._transport = transport
        self._opts = options or CliOptions()
        # groupId -> cached leader PeerId
        self._leaders: dict[str, PeerId] = {}

    # -- leader discovery ----------------------------------------------------

    async def get_leader(self, group_id: str, conf: Configuration
                         ) -> Optional[PeerId]:
        """Ask each configured peer who leads; first definite answer wins."""
        for peer in conf.list_all():
            try:
                resp = await self._transport.call(
                    peer.endpoint, "cli_get_leader",
                    GetLeaderRequest(group_id=group_id, peer_id=str(peer)),
                    self._opts.timeout_ms)
            except RpcError:
                continue
            if resp.success and resp.leader_id:
                leader = PeerId.parse(resp.leader_id)
                self._leaders[group_id] = leader
                return leader
        return None

    async def get_peers(self, group_id: str, conf: Configuration,
                        only_alive: bool = False) -> list[PeerId]:
        resp = await self._peers_rpc(group_id, conf, only_alive)
        return [PeerId.parse(p) for p in resp.peers]

    async def get_learners(self, group_id: str, conf: Configuration
                           ) -> list[PeerId]:
        resp = await self._peers_rpc(group_id, conf, False)
        return [PeerId.parse(p) for p in resp.learners]

    async def get_configuration(self, group_id: str, conf: Configuration
                                ) -> Configuration:
        """Voters, learners AND witness flags in one round trip."""
        resp = await self._peers_rpc(group_id, conf, False)
        return Configuration(
            [PeerId.parse(p) for p in resp.peers],
            [PeerId.parse(p) for p in resp.learners],
            [PeerId.parse(p) for p in getattr(resp, "witnesses", [])])

    async def _peers_rpc(self, group_id: str, conf: Configuration,
                         only_alive: bool) -> GetPeersResponse:
        leader = await self._require_leader(group_id, conf)
        try:
            resp = await self._transport.call(
                leader.endpoint, "cli_get_peers",
                GetPeersRequest(group_id=group_id, peer_id=str(leader),
                                only_alive=only_alive),
                self._opts.timeout_ms)
        except RpcError:
            self._leaders.pop(group_id, None)  # dead leader: force rediscovery
            raise
        if not resp.success:
            self._leaders.pop(group_id, None)
            raise RpcError(Status.error(RaftError.EINTERNAL, "get_peers failed"))
        return resp

    async def _require_leader(self, group_id: str, conf: Configuration
                              ) -> PeerId:
        leader = self._leaders.get(group_id)
        if leader is None:
            leader = await self.get_leader(group_id, conf)
        if leader is None:
            raise RpcError(Status.error(
                RaftError.EAGAIN, f"no leader for group {group_id}"))
        return leader

    # -- admin ops -----------------------------------------------------------

    async def add_peer(self, group_id: str, conf: Configuration,
                       peer: PeerId, witness: bool = False) -> Status:
        return await self._leader_op(
            group_id, conf, "cli_add_peer",
            lambda leader: AddPeerRequest(
                group_id=group_id, peer_id=str(leader), adding=str(peer),
                witness=witness))

    async def add_witness(self, group_id: str, conf: Configuration,
                          peer: PeerId) -> Status:
        """Add a WITNESS voter: votes + acks metadata appends, stores no
        log payload, never leads — a 2+1 geo topology's cheap third
        vote (docs/operations.md "Geo deployment runbook")."""
        return await self.add_peer(group_id, conf, peer, witness=True)

    async def remove_witness(self, group_id: str, conf: Configuration,
                             peer: PeerId) -> Status:
        """Remove a witness voter (same wire op as remove_peer; named
        for operator symmetry with add_witness)."""
        return await self.remove_peer(group_id, conf, peer)

    async def remove_peer(self, group_id: str, conf: Configuration,
                          peer: PeerId) -> Status:
        return await self._leader_op(
            group_id, conf, "cli_remove_peer",
            lambda leader: RemovePeerRequest(
                group_id=group_id, peer_id=str(leader), removing=str(peer)))

    async def change_peers(self, group_id: str, conf: Configuration,
                           new_conf: Configuration) -> Status:
        return await self._leader_op(
            group_id, conf, "cli_change_peers",
            lambda leader: ChangePeersRequest(
                group_id=group_id, peer_id=str(leader),
                new_peers=[str(p) for p in new_conf.peers],
                new_learners=[str(p) for p in new_conf.learners],
                new_witnesses=[str(p) for p in new_conf.witnesses]))

    async def reset_peers(self, group_id: str, peer: PeerId,
                          new_conf: Configuration) -> Status:
        """Directly reset one peer's conf (dangerous; quorum-loss recovery)."""
        resp = await self._transport.call(
            peer.endpoint, "cli_reset_peers",
            ResetPeersRequest(
                group_id=group_id, peer_id=str(peer),
                new_peers=[str(p) for p in new_conf.peers],
                new_learners=[str(p) for p in new_conf.learners],
                new_witnesses=[str(p) for p in new_conf.witnesses]),
            self._opts.timeout_ms)
        return Status(resp.code, resp.msg)

    async def snapshot(self, group_id: str, peer: PeerId) -> Status:
        resp = await self._transport.call(
            peer.endpoint, "cli_snapshot",
            SnapshotRequest(group_id=group_id, peer_id=str(peer)),
            self._opts.timeout_ms)
        return Status(resp.code, resp.msg)

    async def transfer_leader(self, group_id: str, conf: Configuration,
                              transferee: PeerId) -> Status:
        st = await self._leader_op(
            group_id, conf, "cli_transfer_leader",
            lambda leader: TransferLeaderRequest(
                group_id=group_id, peer_id=str(leader),
                transferee=str(transferee)))
        if st.is_ok():
            self._leaders.pop(group_id, None)
        return st

    async def add_learners(self, group_id: str, conf: Configuration,
                           learners: list[PeerId]) -> Status:
        return await self._leader_op(
            group_id, conf, "cli_add_learners",
            lambda leader: AddLearnersRequest(
                group_id=group_id, peer_id=str(leader),
                learners=[str(p) for p in learners]))

    async def remove_learners(self, group_id: str, conf: Configuration,
                              learners: list[PeerId]) -> Status:
        return await self._leader_op(
            group_id, conf, "cli_remove_learners",
            lambda leader: RemoveLearnersRequest(
                group_id=group_id, peer_id=str(leader),
                learners=[str(p) for p in learners]))

    async def reset_learners(self, group_id: str, conf: Configuration,
                             learners: list[PeerId]) -> Status:
        return await self._leader_op(
            group_id, conf, "cli_reset_learners",
            lambda leader: ResetLearnersRequest(
                group_id=group_id, peer_id=str(leader),
                learners=[str(p) for p in learners]))

    async def describe_metrics(self, endpoint: str) -> str:
        """Scrape one store's live metrics (Prometheus text) over the
        admin transport — the wire-borne equivalent of GET /metrics on
        its optional HTTP listener.  Addressed per ENDPOINT (store
        scope, not group scope): every region group on the store is
        folded into the one rendering."""
        resp = await self._transport.call(
            endpoint, "cli_describe_metrics", DescribeMetricsRequest(),
            self._opts.timeout_ms)
        if not getattr(resp, "success", False):
            raise RpcError(Status.error(RaftError.EINTERNAL,
                                        f"describe_metrics on {endpoint}"))
        return resp.text

    async def rebalance(self, balance_group_ids: list[str],
                        conf: Configuration) -> Status:
        """Spread leaders of the given groups evenly over peers.

        Reference: ``CliServiceImpl#rebalance`` — computes the expected
        average leader count per peer and transfers leadership off
        overloaded peers.
        """
        if not balance_group_ids:
            return Status.OK()
        # voters only — learners can't lead; witnesses vote but can
        # never lead either, so they are not balancing targets
        peers = [p for p in conf.peers if not conf.is_witness(p)]
        if not peers:
            return Status.error(RaftError.EINVAL, "empty conf")
        expected = (len(balance_group_ids) + len(peers) - 1) // len(peers)
        counts: dict[str, int] = {str(p): 0 for p in peers}
        last_failure: Optional[Status] = None
        for gid in balance_group_ids:
            leader = await self.get_leader(gid, conf)
            if leader is None:
                last_failure = Status.error(RaftError.EAGAIN,
                                            f"no leader for group {gid}")
                continue
            counts.setdefault(str(leader), 0)
            counts[str(leader)] += 1
            if counts[str(leader)] > expected:
                target = min(peers, key=lambda p: counts.get(str(p), 0))
                st = await self.transfer_leader(gid, conf, target)
                if st.is_ok():
                    counts[str(leader)] -= 1
                    counts[str(target)] = counts.get(str(target), 0) + 1
                else:
                    last_failure = st
        return last_failure if last_failure is not None else Status.OK()

    # -- retry engine --------------------------------------------------------

    async def _leader_op(self, group_id: str, conf: Configuration,
                         method: str, make_req) -> Status:
        last = Status.error(RaftError.EAGAIN, "no attempt")
        attempt = 0
        busy_left = self._opts.busy_max_retry
        busy_backoff_ms = self._opts.busy_backoff_ms
        while attempt < self._opts.max_retry:
            try:
                leader = await self._require_leader(group_id, conf)
            except RpcError as e:
                last = e.status
                attempt += 1
                await asyncio.sleep(self._opts.retry_interval_ms / 1000.0)
                continue
            try:
                resp = await self._transport.call(
                    leader.endpoint, method, make_req(leader),
                    self._opts.timeout_ms)
            except RpcError as e:
                last = e.status
                attempt += 1
                self._leaders.pop(group_id, None)
                await asyncio.sleep(self._opts.retry_interval_ms / 1000.0)
                continue
            if resp.code == 0:
                return Status.OK()
            last = Status(resp.code, resp.msg)
            if resp.code == int(RaftError.EPERM):  # stale leader; refresh
                attempt += 1
                self._leaders.pop(group_id, None)
                await asyncio.sleep(self._opts.retry_interval_ms / 1000.0)
                continue
            if resp.code == int(RaftError.EBUSY):
                # another change in flight: transient by contract —
                # bounded exponential backoff, leader cache KEPT (busy
                # does not mean wrong leader)
                if busy_left <= 0:
                    return Status(
                        int(RaftError.EBUSY),
                        f"still busy after {self._opts.busy_max_retry} "
                        f"retries: {resp.msg}")
                busy_left -= 1
                await asyncio.sleep(busy_backoff_ms / 1000.0)
                busy_backoff_ms = min(busy_backoff_ms * 2,
                                      self._opts.busy_backoff_max_ms)
                continue
            return last  # definite rejection (EINVAL, ECATCHUP, ...)
        return last


def describe_status(st: Status) -> str:
    """Operator-facing classification of an admin-op status: makes
    'busy, retry later' distinguishable from 'your conf is wrong' at a
    glance (and by exit-code policy in examples/admin.py)."""
    if st.is_ok():
        return "OK"
    code = st.raft_error
    if code == RaftError.EBUSY:
        kind = "busy (transient — another membership change or a " \
               "leadership transfer is in flight; retry later)"
    elif code == RaftError.EINVAL:
        kind = "invalid request (check the configuration argument)"
    elif code == RaftError.ECATCHUP:
        kind = "new peers failed to catch up (are they running and " \
               "reachable?)"
    elif code == RaftError.EPERM:
        kind = "not leader (leadership moved; rediscover and retry)"
    elif code == RaftError.EAGAIN:
        kind = "no leader found (cluster electing or unreachable)"
    else:
        kind = "failed"
    return f"error[{code.name if code else st.code}]: {kind}: {st.error_msg}"
