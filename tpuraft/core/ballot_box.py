"""BallotBox: quorum commit tracking for one group (host runtime).

Reference parity: ``core:core/BallotBox`` + ``core:entity/Ballot``
(SURVEY.md §3.1 north-star hot path).  Reformulated: instead of one Ballot
object per pending log index, the commit point is the quorum order
statistic of the peers' matchIndex vector — the formulation proved
equivalent to per-index ballots in tests/test_ops_ballot.py and executed
batched on device by tpuraft.ops for the multi-raft engine.  During a
membership change the double-quorum (joint consensus) applies to the whole
pending window, which is conservative-safe (old conf is a subset of the
joint requirement).

This host class handles ONE group in scalar numpy/python — the
MultiRaftEngine replaces G of these with one [G, P] kernel call per tick.
"""

from __future__ import annotations

from typing import Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.entity import PeerId
from tpuraft.errors import Status


def commit_point(match: dict[PeerId, int], conf: Configuration,
                 old_conf: Configuration) -> int:
    """Scalar mirror of ops.ballot.joint_quorum_match_index PLUS the
    witness data-clamp — the device kernel carries the same clamp
    (ops.ballot.witness_commit_clamp), and the two are differentially
    enumerated against each other in test_ops_tick.

    Witness-aware: witnesses are ordinary voters in the order statistic
    (they ack metadata appends), but the commit point is additionally
    CLAMPED to the best DATA replica's match — an index no data voter
    has stored must never commit, however many witness acks it holds.
    A "data replica" is a voter that is a witness in NEITHER config:
    the replication plane strips payloads for a peer flagged witness in
    either conf (Node#peer_is_witness), so a data-in-old voter being
    demoted to witness holds no payload for joint-window entries and
    must not anchor the clamp.  Normally a no-op (the leader is always
    a data replica and its own match row covers the tail), so this is
    defense in depth against a witness-only quorum certifying
    payload-free commits (the ISSUE's witness-majority-must-not-commit
    case, enumerated in tests/test_witness.py against util/quorum.py)."""

    def order_stat(peers: list[PeerId]) -> int:
        vals = sorted((match.get(p, 0) for p in peers), reverse=True)
        if not vals:
            return -1
        return vals[len(peers) // 2]  # q-th largest, q = n//2+1

    new_q = order_stat(conf.peers)
    if not old_conf.is_empty():
        new_q = min(new_q, order_stat(old_conf.peers))
    if conf.witnesses or old_conf.witnesses:
        wits = set(conf.witnesses) | set(old_conf.witnesses)
        data = (set(conf.peers) | set(old_conf.peers)) - wits
        data_best = max((match.get(p, 0) for p in data), default=0)
        new_q = min(new_q, data_best)
    return new_q


# graftcheck: loop-confined — commit_at/update_conf run on the node's
# event loop; the engine-backed TpuBallotBox keeps the same contract
class BallotBox:
    def __init__(self, on_committed: Callable[[int], None]):
        self._on_committed = on_committed  # FSMCaller#onCommitted
        self.last_committed_index = 0
        self.pending_index = 0  # first index of current leadership; 0 = not leader
        self._match: dict[PeerId, int] = {}

    # -- leader side ---------------------------------------------------------

    def reset_pending_index(self, new_pending_index: int) -> None:
        """At becomeLeader: only entries from here on may be quorum-committed
        (Raft §5.4.2 — reference: BallotBox#resetPendingIndex)."""
        self.pending_index = new_pending_index
        self._match.clear()

    def clear_pending(self) -> None:
        self.pending_index = 0
        self._match.clear()

    def commit_at(self, peer: PeerId, match_index: int, conf: Configuration,
                  old_conf: Configuration) -> bool:
        """Record peer's acked matchIndex; advance commit if quorum reached.
        Returns True if the commit index advanced."""
        if self.pending_index == 0:
            return False
        prev = self._match.get(peer, 0)
        if match_index <= prev:
            return False
        self._match[peer] = match_index
        point = commit_point(self._match, conf, old_conf)
        if point < self.pending_index or point <= self.last_committed_index:
            return False
        self.last_committed_index = point
        self._on_committed(point)
        return True

    def update_conf(self, conf: Configuration, old_conf: Configuration) -> None:
        """Membership changed: drop match rows for peers no longer in any
        voter/learner set.  Load-bearing for churn: a voter that is
        removed, wiped, and later re-added must re-earn its matchIndex
        from zero — its stale pre-removal row counting toward the quorum
        order statistic would commit entries the reborn peer never
        stored, breaking quorum intersection.  (The engine-backed
        TpuBallotBox maintains device voter masks in its override.)"""
        members = set(conf.peers) | set(old_conf.peers) \
            | set(conf.learners) | set(old_conf.learners)
        for peer in [p for p in self._match if p not in members]:
            del self._match[peer]

    def close(self) -> None:
        """SPI hook: release engine resources (no-op for the scalar box)."""

    # -- follower side -------------------------------------------------------

    def set_last_committed_index(self, index: int) -> bool:
        """Follower: leader said commit has reached ``index``."""
        if self.pending_index != 0:
            return False  # leaders ignore remote commit notices
        if index <= self.last_committed_index:
            return False
        self.last_committed_index = index
        self._on_committed(index)
        return True
