"""NodeManager: groupId -> Node routing on one shared RPC endpoint.

Reference parity: ``core:NodeManager`` + the per-request processors bound
to one RpcServer (SURVEY.md §2 "Key structural fact"): N raft groups
multiplex one server; requests route by (group_id, peer_id).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from tpuraft.core.node import Node, State
from tpuraft.entity import PeerId
from tpuraft.rpc.messages import BatchResponse, BeatAck
from tpuraft.errors import RaftError, Status
from tpuraft.rpc.transport import RpcError, RpcServer

LOG = logging.getLogger(__name__)


class NodeManager:
    """One per process endpoint."""

    def __init__(self, server: RpcServer):
        self.server = server
        self._nodes: dict[tuple[str, str], Node] = {}
        # (group, leader) -> (FIFO, worker) of in-order AppendEntries execution
        self._append_lanes: dict[
            tuple[str, str], tuple[asyncio.Queue, asyncio.Task]] = {}
        for method in ("append_entries", "request_vote", "timeout_now",
                       "install_snapshot", "read_index"):
            server.register(method, self._make_handler(method))
        # get_file serves snapshot chunks; routed by reader_id not group
        self._file_readers: dict[int, object] = {}
        self._next_reader_id = 1
        server.register("get_file", self._handle_get_file)
        # coalesced heartbeats (HeartbeatHub): one RPC per endpoint pair
        server.register("multi_heartbeat", self._handle_multi_heartbeat)
        # batched send plane (SendPlane): votes + entry-bearing appends
        # coalesced the same way — O(endpoints) RPCs, O(endpoints)
        # standing sender tasks
        server.register("multi_append", self._handle_multi_append)
        server.register("multi_vote", self._handle_multi_vote)
        # store-wide append rounds (AppendBatcher): every led group's
        # pending entry window toward this endpoint in ONE RPC per
        # window — the write-plane mirror of multi_beat_fast
        server.register("store_append", self._handle_store_append)
        server.register("multi_beat_fast", self._handle_multi_beat_fast)
        # store-level liveness lease (quiescence): one tiny beat per
        # endpoint pair proves a whole store alive while its groups
        # hibernate (HeartbeatHub receiver side)
        server.register("store_lease", self._handle_store_lease)
        self._send_plane = None
        self._heartbeat_hub = None  # created on first coalescing leader
        # at most ONE outstanding beat handler per (group, peer): beats
        # behind a busy node lock must answer EBUSY, not stack a new
        # lock waiter every round (queue flooding starves vote handling)
        self._beat_inflight: set[tuple[str, str]] = set()
        # same guard for batched appends: a stuck node (long fsync /
        # snapshot load) must not accumulate one shielded handler —
        # each carrying a full entry window — per leader retry cycle
        self._append_inflight: set[tuple[str, str]] = set()

    @property
    def heartbeat_hub(self):
        if self._heartbeat_hub is None:
            from tpuraft.core.heartbeat_hub import HeartbeatHub

            self._heartbeat_hub = HeartbeatHub()
        return self._heartbeat_hub

    @property
    def send_plane(self):
        if self._send_plane is None:
            from tpuraft.core.send_plane import SendPlane

            self._send_plane = SendPlane()
        return self._send_plane

    async def _handle_multi_beat_fast(self, request):
        """Beat-plane fast path: steady-state heartbeats processed
        INLINE — no node lock, no per-beat task.  At region density the
        classic per-beat handler fan-out is the dominant idle burn
        (G beats/s, each lock + shielded task on a 1-core host); here a
        beat that matches the receiver's (FOLLOWER, term, leader,
        committed) row just touches the election deadline.  Any
        deviation answers ok=False and the sender follows up with a
        classic full-semantics beat for that group only."""
        acks = []
        for b in request.items:
            node = self._nodes.get((b.group_id, b.peer_id))
            if (node is not None
                    and node.state == State.FOLLOWER
                    and node.current_term == b.term
                    and str(node.leader_id) == b.server_id
                    and b.committed_index
                    <= node.ballot_box.last_committed_index):
                node._ctrl.note_leader_contact()
                node._last_leader_timestamp = node._clock.monotonic()
                ok = True
                if getattr(b, "quiesce", False):
                    # quiesce handshake: join the hibernation ONLY when
                    # this follower is provably at the leader's tail
                    # (the leader's committed == its last index == our
                    # last index and we applied it) — a lagging or
                    # timer-mode follower refuses, keeping the group
                    # active and its election timer live
                    enter = getattr(node._ctrl,
                                    "enter_quiescent_follower", None)
                    ok = (enter is not None
                          and node.log_manager.last_log_index()
                          == b.committed_index
                          and node.ballot_box.last_committed_index
                          == b.committed_index
                          and enter(PeerId.parse(b.server_id).endpoint,
                                    getattr(b, "lease_ms", 0)))
                else:
                    # a NORMAL beat from an active leader: a follower
                    # still hibernating (aborted handshake, leader woke)
                    # resumes fault detection with it
                    node._ctrl.note_activity()
                acks.append(BeatAck(ok=bool(ok), term=node.current_term,
                                    clock_ms=self._clock_ms()))
            else:
                acks.append(BeatAck(
                    ok=False,
                    term=node.current_term if node is not None else 0,
                    clock_ms=self._clock_ms()))
        return BatchResponse(items=acks)

    def _clock_ms(self) -> int:
        """This store's clock reading (monotonic ms) for ack piggyback —
        the peer-skew estimator's raw sample (ISSUE 18)."""
        return int(self.heartbeat_hub.clock.monotonic() * 1000)

    async def _handle_store_lease(self, request):
        """Receiver side of the store-level liveness lease: re-arm the
        sending store's lease; the hub's watcher wakes every dependent
        quiescent group the moment it expires."""
        from tpuraft.rpc.messages import StoreLeaseAck

        deps = self.heartbeat_hub.note_lease_from(
            request.endpoint, request.lease_ms)
        return StoreLeaseAck(ok=True, dependents=deps,
                             clock_ms=self._clock_ms())

    async def _handle_multi_vote(self, request):
        """Fan a vote BatchRequest out concurrently; vote handlers only
        hold the node lock briefly (no disk waits)."""
        from tpuraft.rpc.messages import BatchResponse, ErrorResponse

        async def one(req):
            try:
                node = self._nodes.get((req.group_id, req.peer_id))
                if node is None:
                    return ErrorResponse(int(RaftError.ENOENT),
                                         f"no node for {req.group_id}")
                return await node.handle_request_vote(req)
            except RpcError as e:
                return ErrorResponse(e.status.code, e.status.error_msg)
            except Exception as e:  # noqa: BLE001 — one bad item only
                LOG.exception("multi_vote item failed")
                return ErrorResponse(int(RaftError.EINTERNAL), repr(e))

        acks = await asyncio.gather(*(one(r) for r in request.items))
        return BatchResponse(items=list(acks))

    async def _handle_multi_append(self, request):
        """Fan an AppendEntries BatchRequest out: per TARGET NODE the
        items execute sequentially in batch order (the in-order
        execution contract pipelined replication needs — the sender
        guarantees no cross-RPC races by keeping one RPC in flight per
        endpoint); distinct nodes run concurrently, so their log
        flushes coalesce into the same multilog group-commit round.

        A node that cannot serve an item within half an election
        timeout gets EBUSY for that item AND every later item of the
        same node in this batch (executing later items while the stuck
        one still holds the lane would reorder the group's log writes);
        the shielded handler keeps running, the leader just rolls back
        and re-probes, exactly like a dropped direct RPC."""
        from tpuraft.rpc.messages import BatchResponse

        return BatchResponse(
            items=await self._serve_append_items(request.items))

    async def _handle_store_append(self, request):
        """AppendBatcher's store-wide append round: per-node in-order
        execution like ``multi_append``, but LEAN — one task per node
        run and direct awaits per row instead of the per-item
        shield/wait_for pair.  The per-item EBUSY budget moves to the
        node run: a node that cannot finish its rows within half an
        election timeout answers EBUSY for the unserved tail (the
        handler itself keeps running shielded — cancelling a
        mid-flush append would tear durability ordering).  At region
        density the per-item timer+task machinery was a measurable
        slice of the loop's saturated write path; rounds are already
        windowed sender-side, so the receiver doesn't need a second
        layer of per-item pacing."""
        from tpuraft.rpc.messages import ErrorResponse, StoreAppendResponse

        rows = request.rows
        out: list = [None] * len(rows)
        by_node: dict[tuple[str, str], list[int]] = {}
        for i, req in enumerate(rows):
            by_node.setdefault((req.group_id, req.peer_id), []).append(i)

        async def run_node(key, idxs):
            node = self._nodes.get(key)
            if node is None:
                err = ErrorResponse(int(RaftError.ENOENT),
                                    f"no node for {key[0]}")
                for i in idxs:
                    out[i] = err
                return
            if key in self._append_inflight:
                busy = ErrorResponse(int(RaftError.EBUSY), f"{key[0]} busy")
                for i in idxs:
                    out[i] = busy
                return
            answered = [False]   # round replied: drop any late writes
            # claim the lane SYNCHRONOUSLY, before the task is even
            # scheduled: deferring the add into run_rows opens a
            # window where two concurrent rounds for the same node
            # both pass the busy-check above and interleave the
            # group's log writes (the in-order contract the guard
            # exists for)
            self._append_inflight.add(key)

            async def run_rows():
                try:
                    for i in idxs:
                        try:
                            r = await node.handle_append_entries(rows[i])
                        except RpcError as e:
                            r = ErrorResponse(e.status.code,
                                              e.status.error_msg)
                        except asyncio.CancelledError:
                            raise
                        except Exception as e:  # noqa: BLE001
                            LOG.exception("store_append row failed")
                            r = ErrorResponse(int(RaftError.EINTERNAL),
                                              repr(e))
                        if answered[0]:
                            return  # reply already serialized: too late
                        out[i] = r
                finally:
                    self._append_inflight.discard(key)

            budget = node.options.election_timeout_ms / 1000.0 / 2
            task = asyncio.ensure_future(run_rows())
            try:
                await asyncio.wait_for(asyncio.shield(task), budget)
            except asyncio.TimeoutError:
                # the node is stuck (long fsync / snapshot load): EBUSY
                # its unserved tail NOW; the shielded run keeps going
                # (cancelling a mid-flush append tears durability
                # ordering) but may no longer touch this reply
                answered[0] = True
                task.add_done_callback(
                    lambda t: t.cancelled() or t.exception())
                busy = ErrorResponse(int(RaftError.EBUSY),
                                     f"{key[0]} busy")
                for i in idxs:
                    if out[i] is None:
                        out[i] = busy

        if len(by_node) == 1:
            # the common round shape: no gather layer
            key, idxs = next(iter(by_node.items()))
            await run_node(key, idxs)
        else:
            await asyncio.gather(*(run_node(k, v)
                                   for k, v in by_node.items()))
        return StoreAppendResponse(acks=out)

    async def _serve_append_items(self, items) -> list:
        from tpuraft.rpc.messages import ErrorResponse

        out: list = [None] * len(items)
        by_node: dict[tuple[str, str], list[int]] = {}
        for i, req in enumerate(items):
            by_node.setdefault((req.group_id, req.peer_id), []).append(i)

        async def run_node(key, idxs):
            node = self._nodes.get(key)
            if node is None:
                err = ErrorResponse(int(RaftError.ENOENT),
                                    f"no node for {key[0]}")
                for i in idxs:
                    out[i] = err
                return
            if key in self._append_inflight:
                # a previous window's handler is still stuck on this
                # node: answering EBUSY NOW (without spawning) keeps
                # leader retries from stacking one shielded handler —
                # each holding a full entry window — per cycle
                busy = ErrorResponse(int(RaftError.EBUSY),
                                     f"{key[0]} busy")
                for i in idxs:
                    out[i] = busy
                return
            budget = node.options.election_timeout_ms / 1000.0 / 2
            for pos, i in enumerate(idxs):
                try:
                    self._append_inflight.add(key)
                    task = asyncio.ensure_future(
                        node.handle_append_entries(items[i]))

                    def _done(t, key=key):
                        self._append_inflight.discard(key)
                        if not t.cancelled():
                            t.exception()

                    task.add_done_callback(_done)
                    out[i] = await asyncio.wait_for(
                        asyncio.shield(task), budget)
                except asyncio.TimeoutError:
                    busy = ErrorResponse(int(RaftError.EBUSY),
                                         f"{key[0]} busy")
                    for j in idxs[pos:]:
                        out[j] = busy
                    return
                except RpcError as e:
                    out[i] = ErrorResponse(e.status.code,
                                           e.status.error_msg)
                except Exception as e:  # noqa: BLE001
                    LOG.exception("multi_append item failed")
                    out[i] = ErrorResponse(int(RaftError.EINTERNAL),
                                           repr(e))

        await asyncio.gather(*(run_node(k, v) for k, v in by_node.items()))
        return out

    async def _handle_multi_heartbeat(self, request):
        """Fan a MultiHeartbeatRequest out to the local nodes; each beat
        gets a full per-group response frame, in order."""
        from tpuraft.rpc.messages import (
            ErrorResponse,
            MultiHeartbeatResponse,
            decode_message,
            encode_message,
        )

        import asyncio

        async def one(blob: bytes) -> bytes:
            # concurrent fan-out: each beat takes its own node's lock; a
            # group mid-election (lock held across awaits) must not
            # head-of-line-block the whole batch's ack — the batch only
            # returns when its SLOWEST beat does.  A beat that can't be
            # served promptly answers EBUSY while the real handler keeps
            # running shielded (cancelling a handler mid-step-down would
            # corrupt state); the sender just misses one group's ack for
            # one round, exactly like a dropped direct heartbeat.
            try:
                beat = decode_message(blob)
                key = (beat.group_id, beat.peer_id)
                node = self._nodes.get(key)
                if node is None:
                    raise RpcError(Status.error(
                        RaftError.ENOENT, f"no node for {beat.group_id}"))
                if key in self._beat_inflight:
                    # previous beat still waiting on this node's lock
                    return encode_message(ErrorResponse(
                        int(RaftError.EBUSY), f"{beat.group_id} busy"))
                budget = node.options.election_timeout_ms / 1000.0 / 2
                self._beat_inflight.add(key)
                task = asyncio.ensure_future(
                    node.handle_append_entries(beat))

                def _done(t, key=key):
                    self._beat_inflight.discard(key)
                    if not t.cancelled():
                        t.exception()  # consume if we timed out below

                task.add_done_callback(_done)
                try:
                    resp = await asyncio.wait_for(
                        asyncio.shield(task), budget)
                except asyncio.TimeoutError:
                    resp = ErrorResponse(int(RaftError.EBUSY),
                                         f"{beat.group_id} busy")
            except RpcError as e:
                resp = ErrorResponse(e.status.code, e.status.error_msg)
            except Exception as e:  # noqa: BLE001 — one bad beat only
                LOG.exception("multi_heartbeat beat failed")
                resp = ErrorResponse(int(RaftError.EINTERNAL), repr(e))
            return encode_message(resp)

        acks = await asyncio.gather(*(one(b) for b in request.beats))
        return MultiHeartbeatResponse(acks=list(acks))

    def _make_handler(self, method: str):
        async def handler(request):
            node = self._nodes.get((request.group_id, request.peer_id))
            if node is None:
                raise RpcError(Status.error(
                    RaftError.ENOENT,
                    f"no node for group={request.group_id} peer={request.peer_id}"))
            if method == "append_entries" and request.entries:
                # pipelined replication: a leader keeps a window of
                # AppendEntries in flight; execution here must follow
                # arrival order per (group, leader) or in-window
                # requests would race to the node lock and shuffle,
                # tripping prev-log rejections on every dispatch
                # (reference: AppendEntriesRequestProcessor's
                # per-connection sequence-keyed executors).  EMPTY
                # appends (heartbeats, probes) bypass the lane: a beat
                # must not wait behind a window of synced disk appends
                # (head-of-line blocking would time out ReadIndex SAFE
                # rounds while replication is healthy)
                return await self._ordered_append(node, request)
            return await getattr(node, f"handle_{method}")(request)

        return handler

    async def _ordered_append(self, node: Node, request):
        key = (request.group_id, request.server_id)
        fut = asyncio.get_running_loop().create_future()
        entry = self._append_lanes.get(key)
        if entry is None:
            lane: asyncio.Queue = asyncio.Queue()
            worker = asyncio.ensure_future(self._lane_worker(key, lane))
            entry = self._append_lanes[key] = (lane, worker)
        entry[0].put_nowait((node, request, fut))
        return await fut

    async def _lane_worker(self, key, lane: "asyncio.Queue") -> None:
        idle_reap_s = 60.0
        try:
            while True:
                try:
                    node, req, fut = await asyncio.wait_for(
                        lane.get(), idle_reap_s)
                except asyncio.TimeoutError:
                    if lane.empty():
                        return
                    continue
                try:
                    resp = await node.handle_append_entries(req)
                    if not fut.done():
                        fut.set_result(resp)
                except asyncio.CancelledError:
                    if not fut.done():
                        fut.set_exception(RpcError(Status.error(
                            RaftError.ENODESHUTTING, "lane shut down")))
                    raise
                except Exception as e:  # noqa: BLE001 — per-request error
                    if not fut.done():
                        fut.set_exception(e)
        finally:
            entry = self._append_lanes.get(key)
            if entry is not None and entry[0] is lane:
                del self._append_lanes[key]
                while not lane.empty():
                    _node, _req, fut = lane.get_nowait()
                    if not fut.done():
                        fut.set_exception(RpcError(Status.error(
                            RaftError.ENODESHUTTING, "lane shut down")))

    def add(self, node: Node) -> None:
        self._nodes[(node.group_id, str(node.server_id))] = node

    def remove(self, node: Node) -> None:
        self._nodes.pop((node.group_id, str(node.server_id)), None)
        # tear down this group's append lanes: no worker may linger to
        # execute a queued append against a stopped node, and test
        # teardowns must not see pending-task warnings.  Lanes are keyed
        # by (group, LEADER) and serve every co-hosted node of the
        # group, so only reap once the LAST node of the group leaves —
        # else removing one follower cancels queued appends for its
        # siblings (in-proc topologies host several nodes per server).
        # While siblings remain, still purge THIS node's queued appends:
        # they'd otherwise head-of-line-delay siblings with per-entry
        # EHOSTDOWN rejections and pin the dead node in the queue.
        group_lane_keys = [k for k in self._append_lanes
                           if k[0] == node.group_id]
        if any(g == node.group_id for g, _ in self._nodes):
            for key in group_lane_keys:
                lane, _worker = self._append_lanes[key]
                keep = []
                while not lane.empty():
                    item = lane.get_nowait()
                    if item[0] is node:
                        if not item[2].done():
                            item[2].set_exception(RpcError(Status.error(
                                RaftError.ENODESHUTTING, "node removed")))
                    else:
                        keep.append(item)
                for item in keep:
                    lane.put_nowait(item)
            return
        for key in group_lane_keys:
            lane, worker = self._append_lanes.pop(key)
            worker.cancel()
            while not lane.empty():
                _n, _r, fut = lane.get_nowait()
                if not fut.done():
                    fut.set_exception(RpcError(Status.error(
                        RaftError.ENODESHUTTING, "node removed")))

    def get(self, group_id: str, peer_id: str) -> Optional[Node]:
        return self._nodes.get((group_id, peer_id))

    def list_nodes(self) -> list[Node]:
        return list(self._nodes.values())

    # -- snapshot file service (reference: core:storage/FileService) --------

    def register_file_reader(self, reader) -> int:
        rid = self._next_reader_id
        self._next_reader_id += 1
        self._file_readers[rid] = reader
        return rid

    def unregister_file_reader(self, reader_id: int) -> None:
        self._file_readers.pop(reader_id, None)

    async def _handle_get_file(self, request):
        from tpuraft.rpc.messages import GetFileResponse

        reader = self._file_readers.get(request.reader_id)
        if reader is None:
            raise RpcError(Status.error(
                RaftError.ENOENT, f"no file reader {request.reader_id}"))
        count = request.count
        throttle = getattr(reader, "throttle", None)
        if throttle is not None:
            count = await throttle.acquire_upto(count)
        data, eof = reader.read_file(request.filename, request.offset, count)
        return GetFileResponse(eof=eof, data=data)
