"""NodeManager: groupId -> Node routing on one shared RPC endpoint.

Reference parity: ``core:NodeManager`` + the per-request processors bound
to one RpcServer (SURVEY.md §2 "Key structural fact"): N raft groups
multiplex one server; requests route by (group_id, peer_id).
"""

from __future__ import annotations

import logging
from typing import Optional

from tpuraft.core.node import Node
from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.rpc.transport import RpcError, RpcServer

LOG = logging.getLogger(__name__)


class NodeManager:
    """One per process endpoint."""

    def __init__(self, server: RpcServer):
        self.server = server
        self._nodes: dict[tuple[str, str], Node] = {}
        for method in ("append_entries", "request_vote", "timeout_now",
                       "install_snapshot", "read_index"):
            server.register(method, self._make_handler(method))
        # get_file serves snapshot chunks; routed by reader_id not group
        self._file_readers: dict[int, object] = {}
        self._next_reader_id = 1
        server.register("get_file", self._handle_get_file)

    def _make_handler(self, method: str):
        async def handler(request):
            node = self._nodes.get((request.group_id, request.peer_id))
            if node is None:
                raise RpcError(Status.error(
                    RaftError.ENOENT,
                    f"no node for group={request.group_id} peer={request.peer_id}"))
            return await getattr(node, f"handle_{method}")(request)

        return handler

    def add(self, node: Node) -> None:
        self._nodes[(node.group_id, str(node.server_id))] = node

    def remove(self, node: Node) -> None:
        self._nodes.pop((node.group_id, str(node.server_id)), None)

    def get(self, group_id: str, peer_id: str) -> Optional[Node]:
        return self._nodes.get((group_id, peer_id))

    def list_nodes(self) -> list[Node]:
        return list(self._nodes.values())

    # -- snapshot file service (reference: core:storage/FileService) --------

    def register_file_reader(self, reader) -> int:
        rid = self._next_reader_id
        self._next_reader_id += 1
        self._file_readers[rid] = reader
        return rid

    def unregister_file_reader(self, reader_id: int) -> None:
        self._file_readers.pop(reader_id, None)

    async def _handle_get_file(self, request):
        from tpuraft.rpc.messages import GetFileResponse

        reader = self._file_readers.get(request.reader_id)
        if reader is None:
            raise RpcError(Status.error(
                RaftError.ENOENT, f"no file reader {request.reader_id}"))
        count = request.count
        throttle = getattr(reader, "throttle", None)
        if throttle is not None:
            count = await throttle.acquire_upto(count)
        data, eof = reader.read_file(request.filename, request.offset, count)
        return GetFileResponse(eof=eof, data=data)
