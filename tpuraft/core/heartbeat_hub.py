"""HeartbeatHub: coalesce leader heartbeats across raft groups.

TPU-native multi-raft scaling piece (SURVEY.md §3.5 "batched per-tick
(group, peer) send matrices"; no reference counterpart — the reference
sends one heartbeat RPC per (group, follower) pair).  With thousands of
groups multiplexed on one endpoint, per-group heartbeats cost
O(G x P) RPCs per interval even when idle.  The hub sends ONE
``multi_heartbeat`` RPC per destination endpoint per tick, packing the
empty-AppendEntries beat of every local leader group replicating to
that endpoint; the receiving NodeManager fans the beats out to its
local nodes and returns the acks batched the same way.

Correctness notes:
- Each beat is a full AppendEntriesRequest and each ack a full
  AppendEntriesResponse, processed by the SAME per-replicator logic as
  the direct path (lease acks, step-down on higher term, re-probe on
  lost match) — only the transport envelope is shared.
- A transport failure produces no acks, so leader-lease dead-node
  detection (Node._check_dead_nodes) behaves exactly as with per-group
  heartbeats.
- The ReadIndex (SAFE) quorum round keeps its direct per-group
  heartbeats: its latency is user-facing and must not wait for the next
  hub tick.

Opt in with ``RaftOptions.coalesce_heartbeats = True`` (the node must
be wired to a NodeManager, which owns the hub).

Two drivers share :meth:`pulse`:
- TIMER mode (nodes without an engine): the hub's own clock beats all
  registered replicators each interval.
- ENGINE mode: replicators never register a clock; the device tick's
  ``hb_due`` mask collects every due group and calls ``pulse`` once per
  tick (``MultiRaftEngine._flush_heartbeats``), with deadlines
  phase-aligned to the hb interval so beats batch maximally.

Operating envelope (timer mode): the hub is one shared clock per
process, so a late loop wakeup delays EVERY group's beat at once — a
correlation that independent per-group timers don't have.  Size
election timeouts with headroom over worst-case event-loop latency at
your group count (round 1 measured 64 groups x 3 replicas in one
CPython process needing ~2s timeouts to ride out boot-storm lag; the
engine control plane has since removed the per-group timers — 4096
groups elect in one process at 300ms timeouts through the device
tick — so at scale prefer engine mode).  The timer-mode hub beats at
HALF the per-group heartbeat interval for margin.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Optional

from tpuraft.rpc.messages import (
    BatchRequest,
    CompactBeat,
    MultiHeartbeatRequest,
    MultiHeartbeatResponse,
    decode_message,
    encode_message,
)
from tpuraft.rpc.transport import RpcError, is_no_method

if TYPE_CHECKING:
    from tpuraft.core.replicator import Replicator

LOG = logging.getLogger(__name__)


class HeartbeatHub:
    def __init__(self) -> None:
        # (id(replicator)) -> replicator; grouped by endpoint per tick so
        # registration order never matters
        self._members: dict[int, "Replicator"] = {}
        self._task: Optional[asyncio.Task] = None
        self._inflight: dict[str, asyncio.Task] = {}  # dst -> send task
        self._interval_s = 0.1
        # chunking bound: enough to collapse idle RPC load by an order of
        # magnitude, small enough that a contended group's slow ack only
        # delays its own chunk
        self.max_beats_per_rpc = 16
        # fast beats are data rows, not frames: a straggler answers
        # needs_full instead of delaying its chunk, so chunks can be big
        self.max_fast_beats_per_rpc = 1024
        self.rpcs_sent = 0      # multi_heartbeat RPCs (observability)
        self.beats_sent = 0     # individual group beats carried
        self.fast_beats_sent = 0
        self.fast_fallbacks = 0
        self._fast_ok: dict[str, bool] = {}  # dst lacks multi_beat_fast

    def register(self, replicator: "Replicator") -> None:
        node = replicator._node
        # beat at HALF the per-group heartbeat interval: the hub is one
        # shared clock, so a late wakeup delays every group's beat at
        # once — the margin keeps late beats inside election timeouts
        interval = (node.options.election_timeout_ms
                    / node.options.raft_options.election_heartbeat_factor
                    / 1000.0) / 2
        self._interval_s = min(self._interval_s, interval) \
            if self._members else interval
        self._members[id(replicator)] = replicator
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    def deregister(self, replicator: "Replicator") -> None:
        self._members.pop(id(replicator), None)
        if not self._members and self._task is not None:
            # nothing to beat: stop the loop (register() restarts it) so
            # cluster teardown leaves no dangling task
            self._task.cancel()
            self._task = None
            for t in self._inflight.values():
                t.cancel()
            self._inflight.clear()

    async def shutdown(self) -> None:
        self._members.clear()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self._interval_s)
                await self.tick_once()
        except asyncio.CancelledError:
            return

    async def tick_once(self) -> None:
        self.pulse(list(self._members.values()))

    def pulse(self, replicators: list["Replicator"]) -> None:
        """Beat the given replicators NOW, batched per destination
        endpoint.  Two callers: the hub's own clock (tick_once) and the
        engine's hb_due mask (MultiRaftEngine._flush_heartbeats), which
        passes every due group's replicators in one call so idle beats
        stay O(endpoints) per tick.

        Steady-state beats ride the beat-plane FAST path (CompactBeat
        data, inline lock-free validation on the receiver — see
        NodeManager._handle_multi_beat_fast): at region density the
        classic per-beat handler fan-out is the dominant idle CPU burn.
        A group whose fast beat answers needs-full (term moved,
        committed behind, follower restarted) gets a classic
        full-semantics beat as the follow-up; replicators not yet
        matched, or whose endpoint hasn't advertised the capability,
        take the classic path directly.

        Frames/beats MUST be built here, synchronously: between the
        is_leader() check and an await, a step-down + re-election can
        change the node's term, and a beat built late would claim
        leadership of the NEW term from a node that is now a follower
        (observed as spurious "two leaders in one term" conflicts on
        receivers).  No awaits may separate the check from the build."""
        by_dst_fast: dict[str, list[tuple["Replicator", CompactBeat]]] = {}
        classic: list["Replicator"] = []
        for r in replicators:
            node = r._node
            if not node.is_leader() or not r._running:
                continue
            if (r.peer_multi_hb and r._matched
                    and self._fast_ok.get(r.peer.endpoint, True)):
                committed = min(node.ballot_box.last_committed_index,
                                r.match_index)
                # idle-burn dominator at region density: reuse the beat
                # object while (term, committed) are unchanged — the
                # steady state — instead of rebuilding it every pulse
                cached = getattr(r, "_fast_beat_cache", None)
                if (cached is not None and cached.term == node.current_term
                        and cached.committed_index == committed):
                    beat = cached
                else:
                    beat = CompactBeat(
                        group_id=node.group_id,
                        server_id=str(node.server_id),
                        peer_id=str(r.peer),
                        term=node.current_term,
                        committed_index=committed)
                    r._fast_beat_cache = beat
                by_dst_fast.setdefault(r.peer.endpoint, []).append((r, beat))
                continue
            classic.append(r)
        for dst, pairs in by_dst_fast.items():
            for ci in range(0, len(pairs), self.max_fast_beats_per_rpc):
                chunk = pairs[ci:ci + self.max_fast_beats_per_rpc]
                key = f"fast:{dst}#{ci // self.max_fast_beats_per_rpc}"
                if key in self._inflight:
                    continue
                t = asyncio.ensure_future(self._beat_fast(dst, chunk))
                self._inflight[key] = t
                reps = [r for r, _ in chunk]
                t.add_done_callback(
                    lambda _t, k=key, rs=reps: self._reap(k, _t, rs))
        if classic:
            self._pulse_classic(classic)

    def _reap(self, key: str, t: asyncio.Task,
              fallback: Optional[list["Replicator"]] = None) -> None:
        """Done-callback for beat tasks: always retrieve the exception
        (an unretrieved one is event-loop log spam AND a silently
        missed beat), and give fast-path chunks that died on an
        unexpected error their classic-beat fallback so a persistent
        non-RpcError (e.g. codec failure) can't starve those groups of
        heartbeats until their followers start elections."""
        self._inflight.pop(key, None)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is None:
            return
        LOG.warning("heartbeat batch %s failed: %r", key, exc)
        if fallback:
            self.fast_fallbacks += len(fallback)
            self._pulse_classic([r for r in fallback if r._running])

    def _dispatch_classic(
            self, by_dst: dict[str, list[tuple["Replicator", bytes]]]
    ) -> None:
        # fire-and-track per destination chunk: the tick cadence must NOT
        # wait for RPC round trips (a slow endpoint would stall
        # heartbeats to every other endpoint and trigger elections
        # everywhere), and batches are capped so one contended group's
        # slow ack only couples the fates of its own chunk, not every
        # group on the endpoint pair.  A chunk whose previous RPC is
        # still in flight is skipped this tick.
        for dst, pairs in by_dst.items():
            for ci in range(0, len(pairs), self.max_beats_per_rpc):
                chunk = pairs[ci:ci + self.max_beats_per_rpc]
                key = f"{dst}#{ci // self.max_beats_per_rpc}"
                if key in self._inflight:
                    continue
                t = asyncio.ensure_future(self._beat_endpoint(dst, chunk))
                self._inflight[key] = t
                t.add_done_callback(
                    lambda _t, k=key: self._reap(k, _t))

    async def _beat_fast(self, dst: str,
                         pairs: list[tuple["Replicator", object]]) -> None:
        reps = [r for r, _ in pairs]
        items = [b for _, b in pairs]
        node = reps[0]._node
        self.rpcs_sent += 1
        self.fast_beats_sent += len(items)
        try:
            resp = await node.transport.call(
                dst, "multi_beat_fast", BatchRequest(items=items),
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError as e:
            if is_no_method(e):
                # receiver predates the beat plane: classic beats only
                self._fast_ok[dst] = False
                self.pulse(reps)
            return  # else: silence — dead-node detection, as direct
        if len(resp.items) != len(items):
            # short/overlong response: zip would silently drop trailing
            # replicators' acks — treat the whole chunk as deviating
            LOG.warning("multi_beat_fast %s: %d acks for %d beats",
                        dst, len(resp.items), len(items))
            self.fast_fallbacks += len(reps)
            self._pulse_classic(reps)
            return
        now = time.monotonic()
        fallback: list["Replicator"] = []
        for r, ack in zip(reps, resp.items):
            if not r._running or not r._node.is_leader():
                continue
            if getattr(ack, "ok", False):
                # inline ack bookkeeping: the lease plane only needs the
                # (peer, when) write — no per-ack task, no node lock
                r.last_rpc_ack = now
                r._node.on_peer_ack(r.peer, now)
            else:
                fallback.append(r)
        if fallback:
            # full-semantics follow-up for just the deviating groups
            # (term moved / committed behind / follower restarted)
            self.fast_fallbacks += len(fallback)
            self._pulse_classic(fallback)

    def _pulse_classic(self, replicators: list["Replicator"]) -> None:
        """Classic framed beats only (no fast-path retry) — used for
        fast-beat fallbacks to avoid ping-ponging."""
        by_dst: dict[str, list[tuple["Replicator", bytes]]] = {}
        for r in replicators:
            node = r._node
            if not node.is_leader() or not r._running:
                continue
            frame = encode_message(r.build_heartbeat_request())
            by_dst.setdefault(r.peer.endpoint, []).append((r, frame))
        self._dispatch_classic(by_dst)

    async def _beat_endpoint(self, dst: str,
                             pairs: list[tuple["Replicator", bytes]]
                             ) -> None:
        reps = [r for r, _ in pairs]
        frames = [f for _, f in pairs]
        # any member's transport works; they share the process endpoint
        node = reps[0]._node
        self.rpcs_sent += 1
        self.beats_sent += len(frames)
        try:
            # half-election-timeout budget, like the direct heartbeat
            # path: with the inflight-chunk skip, a lost request must
            # release its chunk quickly or one dropped packet silences
            # up to max_beats_per_rpc groups for a full timeout
            resp: MultiHeartbeatResponse = await node.transport.call(
                dst, "multi_heartbeat",
                MultiHeartbeatRequest(beats=frames),
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError:
            return  # no acks: dead-node detection sees silence, as direct
        if len(resp.acks) != len(frames):
            # a short ack list must read as silence for the WHOLE chunk
            # (dead-node detection semantics), not as acks for whichever
            # prefix zip happens to pair up
            LOG.warning("multi_heartbeat %s: %d acks for %d beats",
                        dst, len(resp.acks), len(frames))
            return
        for r, blob in zip(reps, resp.acks):
            try:
                ack = decode_message(blob)
            except Exception:  # noqa: BLE001 — malformed single ack
                continue
            if not hasattr(ack, "success"):
                continue  # ErrorResponse: that group was unserviceable
            if r._running and r._node.is_leader():
                await r.process_heartbeat_response(ack)
