"""HeartbeatHub: coalesce leader heartbeats across raft groups.

TPU-native multi-raft scaling piece (SURVEY.md §3.5 "batched per-tick
(group, peer) send matrices"; no reference counterpart — the reference
sends one heartbeat RPC per (group, follower) pair).  With thousands of
groups multiplexed on one endpoint, per-group heartbeats cost
O(G x P) RPCs per interval even when idle.  The hub sends ONE
``multi_heartbeat`` RPC per destination endpoint per tick, packing the
empty-AppendEntries beat of every local leader group replicating to
that endpoint; the receiving NodeManager fans the beats out to its
local nodes and returns the acks batched the same way.

Correctness notes:
- Each beat is a full AppendEntriesRequest and each ack a full
  AppendEntriesResponse, processed by the SAME per-replicator logic as
  the direct path (lease acks, step-down on higher term, re-probe on
  lost match) — only the transport envelope is shared.
- A transport failure produces no acks, so leader-lease dead-node
  detection (Node._check_dead_nodes) behaves exactly as with per-group
  heartbeats.
- The ReadIndex (SAFE) quorum round keeps its direct per-group
  heartbeats: its latency is user-facing and must not wait for the next
  hub tick.

Opt in with ``RaftOptions.coalesce_heartbeats = True`` (the node must
be wired to a NodeManager, which owns the hub).

Two drivers share :meth:`pulse`:
- TIMER mode (nodes without an engine): the hub's own clock beats all
  registered replicators each interval.
- ENGINE mode: replicators never register a clock; the device tick's
  ``hb_due`` mask collects every due group and calls ``pulse`` once per
  tick (``MultiRaftEngine._flush_heartbeats``), with deadlines
  phase-aligned to the hb interval so beats batch maximally.

Operating envelope (timer mode): the hub is one shared clock per
process, so a late loop wakeup delays EVERY group's beat at once — a
correlation that independent per-group timers don't have.  TIMER MODE
IS THE LEGACY/SMALL-DEPLOYMENT PATH: at density, run the engine control
plane — the device tick's masks schedule beats with no per-group
timers, the engine now derives election-timeout floors from registered
group count + measured tick cost (TickOptions.density_aware_timeouts),
and idle groups hibernate entirely (RaftOptions.quiesce_after_rounds),
collapsing idle beat traffic to the store-level lease below.  The
timer-mode hub still beats at HALF the per-group heartbeat interval
for margin, and timer-mode nodes neither quiesce nor get derived
floors — size their timeouts by docs/operations.md "Density tuning &
quiescence".

Store-level liveness lease (quiescence): while any LOCAL leader group
is hibernating toward an endpoint, the hub sends ONE tiny
``store_lease`` beat per endpoint pair per interval — O(stores^2)
idle RPCs regardless of group count, and pair-deduped on top: a beat
proves the sender alive and its ack proves the receiver alive, so the
higher endpoint of each pair suppresses its own sender while the
lower's beats flow with margin (``lease_suppressed`` counter), roughly
halving even that.  Receiver side, the hub re-arms the sender's lease
(and credits the beat to its own quiescent leaders toward that store,
as an ack would) and a watcher task wakes every dependent quiescent
group (randomized election timeouts) the moment a lease expires;
sender side, each ack refreshes the engine rows of the quiescent
leader groups behind it and re-arms the acking store's lease, keeping
dead-quorum step-down and leader-lease reads live for hibernating
groups.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Optional

from tpuraft.util import clock as clockmod

from tpuraft.rpc.messages import (
    BatchRequest,
    CompactBeat,
    MultiHeartbeatRequest,
    MultiHeartbeatResponse,
    StoreLeaseBeat,
    decode_message,
    encode_message,
)
from tpuraft.rpc.transport import RpcError, is_no_method

if TYPE_CHECKING:
    from tpuraft.core.replicator import Replicator

LOG = logging.getLogger(__name__)


# graftcheck: loop-confined — one hub per NodeManager, driven by its
# loop's clock task / engine tick; counters and lease maps are lockless
class HeartbeatHub:
    def __init__(self, clock=None) -> None:
        # injectable time plane (ISSUE 18): ALL lease bookkeeping below
        # runs on the store's clock so a per-store clock fault skews
        # sender- and receiver-side lease math coherently
        self.clock = clockmod.resolve(clock)
        # worst-case inter-store clock rate error rho (RaftOptions.
        # clock_drift_bound, threaded by StoreEngine): every lease
        # duration granted BY another store's clock but timed on OURS is
        # shrunk by (1 - rho) — zero-margin legacy accounting at 0.0
        self.clock_drift_bound = 0.0
        # peer-skew estimator (ClockSentinel) fed by every beat ack that
        # carries the responder's clock reading; None = no detection
        self.clock_sentinel = None
        # (id(replicator)) -> replicator; grouped by endpoint per tick so
        # registration order never matters
        self._members: dict[int, "Replicator"] = {}
        self._task: Optional[asyncio.Task] = None
        self._inflight: dict[str, asyncio.Task] = {}  # dst -> send task
        self._interval_s = 0.1
        # chunking bound: enough to collapse idle RPC load by an order of
        # magnitude, small enough that a contended group's slow ack only
        # delays its own chunk
        self.max_beats_per_rpc = 16
        # fast beats are data rows, not frames: a straggler answers
        # needs_full instead of delaying its chunk, so chunks can be big
        self.max_fast_beats_per_rpc = 1024
        self.rpcs_sent = 0      # multi_heartbeat RPCs (observability)
        self.beats_sent = 0     # individual group beats carried
        self.fast_beats_sent = 0
        self.fast_fallbacks = 0
        # -- load-adaptive cadence widening ---------------------------------
        # at density (1024 groups x 3 replicas) the hub builds ~2000 beat
        # rows/s of pure standing load; when a pulse carries many rows the
        # hub stretches its sleep toward load_widen_max x the base interval.
        # The base interval is eto/factor/2 (register() above), so the cap
        # of 2.0 only relaxes cadence back to the classic per-group
        # heartbeat interval — still half the election timeout, still safe.
        self.load_widen_rows = 512   # rows/pulse that saturate the widening
        self.load_widen_max = 2.0
        self._widen = 1.0            # EMA'd widening factor (>= 1.0)
        self.widened_pulses = 0      # pulses sent while meaningfully widened
        self._fast_ok: dict[str, bool] = {}  # dst lacks multi_beat_fast
        # -- store-level liveness lease (quiescence) -------------------------
        # sender: dst endpoint -> {id(engine): [engine, transport,
        # src_endpoint, refcount, min_eto_ms]} — one lease beat per dst
        # per interval while any local leader group hibernates toward it
        self._lease_targets: dict[str, dict[int, list]] = {}
        self._lease_task: Optional[asyncio.Task] = None
        # sender: dst -> monotonic time of the last successful lease ack
        # (store_lease_quorum_ok consults this for hibernating leaders)
        # — ALSO refreshed by an incoming beat from dst: a store that
        # beats us is just as provably alive as one that acks us, which
        # is what lets the pair-dedupe below halve idle lease traffic
        self._lease_ack_at: dict[str, float] = {}
        # receiver: src endpoint -> monotonic lease expiry deadline
        self._lease_from: dict[str, float] = {}
        # receiver: src endpoint -> set of EngineControls to wake on expiry
        self._lease_deps: dict[str, set] = {}
        self._lease_watch_task: Optional[asyncio.Task] = None
        # nudges the watcher out of its sleep-to-horizon when a NEW
        # dependency may carry an earlier deadline (so the watcher can
        # sleep until the actual next expiry — minutes at derived
        # timeouts — instead of polling at a fixed sub-second cadence)
        self._lease_watch_nudge = asyncio.Event()
        # lease/quiescence counters (surfaced via describe + soak stats)
        self.lease_rpcs_sent = 0
        self.lease_acks = 0
        self.lease_beats_seen = 0   # receiver side
        self.lease_expiries = 0
        self.lease_suppressed = 0   # pair-dedupe: rounds we rode the
        # peer's beats instead of sending our own
        self.groups_quiesced = 0
        self.groups_woken = 0
        # gray-failure signal sink: the hosting store's HealthTracker
        # (set by StoreEngine).  Every beat RPC the hub already sends
        # doubles as a per-endpoint RTT probe — no extra traffic.
        self.health = None
        from tpuraft.util import describer
        from tpuraft.util.metrics import MetricRegistry

        # one registry per hub, gauges bound to the live counters — the
        # beat-plane sibling of Node.metrics (util/metrics.py idiom);
        # snapshot() is what the soak stats line and benches read
        self.metrics = MetricRegistry()
        for name in ("rpcs_sent", "beats_sent", "fast_beats_sent",
                     "fast_fallbacks", "groups_quiesced", "groups_woken",
                     "lease_rpcs_sent", "lease_acks", "lease_beats_seen",
                     "lease_expiries", "lease_suppressed", "widened_pulses"):
            self.metrics.gauge(f"hub.{name}",
                               lambda n=name: getattr(self, n))
        self.metrics.gauge("hub.widen_factor", lambda: self._widen)
        describer.register(self)

    def register(self, replicator: "Replicator") -> None:
        node = replicator._node
        # beat at HALF the per-group heartbeat interval: the hub is one
        # shared clock, so a late wakeup delays every group's beat at
        # once — the margin keeps late beats inside election timeouts
        interval = (node.options.election_timeout_ms
                    / node.options.raft_options.election_heartbeat_factor
                    / 1000.0) / 2
        self._interval_s = min(self._interval_s, interval) \
            if self._members else interval
        self._members[id(replicator)] = replicator
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    def deregister(self, replicator: "Replicator") -> None:
        self._members.pop(id(replicator), None)
        if not self._members and self._task is not None:
            # nothing to beat: stop the loop (register() restarts it) so
            # cluster teardown leaves no dangling task
            self._task.cancel()
            self._task = None
            for t in self._inflight.values():
                t.cancel()
            self._inflight.clear()

    async def shutdown(self) -> None:
        self._members.clear()
        self._lease_targets.clear()
        self._lease_deps.clear()
        for task in (self._task, self._lease_task, self._lease_watch_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        self._task = self._lease_task = self._lease_watch_task = None
        from tpuraft.util import describer

        describer.unregister(self)

    def describe(self) -> str:
        """Hub counters for operators (registered with util.describer —
        the beat-plane counterpart of Node#describe)."""
        return (f"HeartbeatHub<members={len(self._members)} "
                f"rpcs_sent={self.rpcs_sent} beats_sent={self.beats_sent} "
                f"fast_beats_sent={self.fast_beats_sent} "
                f"fast_fallbacks={self.fast_fallbacks} "
                f"quiesced={self.groups_quiesced} woken={self.groups_woken} "
                f"lease_rpcs={self.lease_rpcs_sent} "
                f"lease_acks={self.lease_acks} "
                f"lease_beats_seen={self.lease_beats_seen} "
                f"lease_expiries={self.lease_expiries} "
                f"lease_suppressed={self.lease_suppressed} "
                f"lease_targets={len(self._lease_targets)} "
                f"lease_deps={sum(map(len, self._lease_deps.values()))} "
                f"widen={self._widen:.2f} "
                f"widened_pulses={self.widened_pulses}>")

    def counters(self) -> dict:
        """Counter snapshot (soak stats line / tests)."""
        return {
            "rpcs_sent": self.rpcs_sent,
            "beats_sent": self.beats_sent,
            "fast_beats_sent": self.fast_beats_sent,
            "fast_fallbacks": self.fast_fallbacks,
            "groups_quiesced": self.groups_quiesced,
            "groups_woken": self.groups_woken,
            "lease_rpcs_sent": self.lease_rpcs_sent,
            "lease_acks": self.lease_acks,
            "lease_beats_seen": self.lease_beats_seen,
            "lease_expiries": self.lease_expiries,
            "lease_suppressed": self.lease_suppressed,
            "widened_pulses": self.widened_pulses,
        }

    # -- store-level liveness lease (sender side) ----------------------------

    def lease_add(self, dst: str, engine, transport, src_endpoint: str,
                  eto_ms: int) -> None:
        """A local leader group hibernated toward ``dst``: keep its
        store's liveness proven by one lease beat per interval."""
        entries = self._lease_targets.setdefault(dst, {})
        ent = entries.get(id(engine))
        if ent is None:
            entries[id(engine)] = [engine, transport, src_endpoint, 1,
                                   eto_ms]
        else:
            ent[3] += 1
            ent[4] = min(ent[4], eto_ms)
        if self._lease_task is None or self._lease_task.done():
            self._lease_task = asyncio.ensure_future(self._lease_loop())

    def lease_remove(self, dst: str, engine) -> None:
        entries = self._lease_targets.get(dst)
        if entries is None:
            return
        ent = entries.get(id(engine))
        if ent is None:
            return
        ent[3] -= 1
        if ent[3] <= 0:
            del entries[id(engine)]
        if not entries:
            del self._lease_targets[dst]

    def lease_ack_fresh(self, dst: str, within_ms: int) -> bool:
        """Sender-side store-lease freshness: the window shrinks by the
        drift bound — ``within_ms`` is what the RECEIVER grants on ITS
        clock, and ours may run up to rho slow, so trusting the full
        window would let our 'fresh' outlive the receiver's grant (the
        heartbeat_hub.py:283-vs-379 zero-margin hole, ISSUE 18)."""
        at = self._lease_ack_at.get(dst)
        if at is None:
            return False
        within_ms *= (1.0 - self.clock_drift_bound)
        return (self.clock.monotonic() - at) * 1000 < within_ms

    def _note_peer_clock(self, dst: str, ack, t0: float, now: float) -> None:
        """Feed the skew estimator from an ack's piggybacked clock
        reading (BeatAck/StoreLeaseAck ``clock_ms``, 0 = old peer)."""
        sentinel = self.clock_sentinel
        if sentinel is None:
            return
        clock_ms = getattr(ack, "clock_ms", 0)
        if clock_ms:
            sentinel.observe(dst, clock_ms / 1000.0, t0, now)

    async def _lease_loop(self) -> None:
        """ONE store_lease RPC per dst endpoint per interval — the whole
        idle cost of a hibernated deployment.  Interval = min dependent
        eto / 4, so a silent store misses ~4 beats before its lease
        expires — inside the normal fault-detection envelope."""
        try:
            while self._lease_targets:
                min_eto = min(ent[4] for entries in
                              self._lease_targets.values()
                              for ent in entries.values())
                await asyncio.sleep(max(0.02, min_eto / 4000.0))
                for dst, entries in list(self._lease_targets.items()):
                    ents = list(entries.values())
                    if not ents:
                        continue
                    # pair dedupe: a lease beat is a BIDIRECTIONAL
                    # liveness proof (the beat proves the sender alive,
                    # its ack proves the receiver alive), so only one
                    # side of each endpoint pair needs to send.  The
                    # higher endpoint rides the lower's beats while they
                    # flow with margin to spare, and resumes its own the
                    # moment they thin out (peer died, or stopped having
                    # leaders toward us) — the fault-detection envelope
                    # is unchanged, the idle RPC rate halves.
                    if ents[0][2] > dst:
                        margin = (self._lease_from.get(dst, 0.0)
                                  - self.clock.monotonic())
                        if margin > min(e[4] for e in ents) / 2000.0:
                            self.lease_suppressed += 1
                            continue
                    t = asyncio.ensure_future(self._lease_beat(dst, ents))
                    t.add_done_callback(
                        lambda tt: tt.cancelled() or tt.exception())
                # lease rounds drive the (otherwise fully idle) engines'
                # ticks, so quiescent-leader step_down staleness is
                # re-evaluated at lease cadence even with zero traffic
                seen = set()
                for entries in self._lease_targets.values():
                    for ent in entries.values():
                        if id(ent[0]) not in seen:
                            seen.add(id(ent[0]))
                            ent[0].mark_dirty()
        except asyncio.CancelledError:
            return
        finally:
            self._lease_task = None

    async def _lease_beat(self, dst: str, ents: list) -> None:
        engine_list = [ent[0] for ent in ents]
        transport = ents[0][1]
        src = ents[0][2]
        lease_ms = min(ent[4] for ent in ents)
        self.lease_rpcs_sent += 1
        t0 = self.clock.monotonic()
        try:
            ack = await transport.call(
                dst, "store_lease",
                StoreLeaseBeat(endpoint=src, lease_ms=lease_ms),
                timeout_ms=max(1, lease_ms // 2))
        except RpcError:
            return  # silence: rows go stale -> step_down, as designed
        self.lease_acks += 1
        now = self.clock.monotonic()
        self._note_peer_clock(dst, ack, t0, now)
        self._lease_ack_at[dst] = now
        for engine in engine_list:
            engine.note_store_ack(dst)
        # the ack also proves dst alive for OUR quiescent followers
        # (pair dedupe: dst may be riding these beats instead of
        # sending its own, so this re-arm is their only refresh) —
        # drift-padded like note_lease_from: the duration is granted on
        # OUR clock here but consumed against dst's liveness, and the
        # symmetric pad keeps both arming paths identical
        deadline = now + lease_ms / 1000.0 * (1.0 - self.clock_drift_bound)
        if deadline > self._lease_from.get(dst, 0.0):
            self._lease_from[dst] = deadline

    # -- store-level liveness lease (receiver side) --------------------------

    def note_lease_from(self, src: str, lease_ms: int) -> int:
        """An incoming store_lease beat: re-arm ``src``'s lease.
        Returns the dependent count (ack observability)."""
        self.lease_beats_seen += 1
        now = self.clock.monotonic()
        # receiver-side drift pad (ISSUE 18 satellite): ``lease_ms`` is
        # a duration granted on the SENDER's clock but timed out on
        # ours — if ours runs up to rho slow, the unpadded deadline
        # silently extends the lease past the sender's intent, so the
        # receiver honors only (1 - rho) of the grant
        deadline = now + lease_ms / 1000.0 * (1.0 - self.clock_drift_bound)
        if deadline > self._lease_from.get(src, 0.0):
            self._lease_from[src] = deadline
        # the beat also proves src alive for OUR quiescent leaders
        # toward it — exactly what an ack of our own beat would prove
        # (pair dedupe: while src keeps beating us, our sender skips
        # its half of the pair and this is the leaders' only refresh)
        entries = self._lease_targets.get(src)
        if entries:
            self._lease_ack_at[src] = now
            for ent in list(entries.values()):
                ent[0].note_store_ack(src)
        return len(self._lease_deps.get(src, ()))

    def lease_fresh(self, src: str) -> bool:
        return self._lease_from.get(src, 0.0) > self.clock.monotonic()

    def lease_depend(self, src: str, ctrl, lease_ms: int) -> None:
        """A local quiescent follower group delegates liveness of its
        leader's store to this lease.  Registration arms the lease (the
        quiesce beat itself just proved the store alive)."""
        self._lease_deps.setdefault(src, set()).add(ctrl)
        self.note_lease_from(src, lease_ms)
        self.lease_beats_seen -= 1  # registration is not a beat
        self._lease_watch_nudge.set()  # new dep may have an earlier
        # deadline than the watcher's current sleep-to-horizon
        if self._lease_watch_task is None or self._lease_watch_task.done():
            self._lease_watch_task = asyncio.ensure_future(
                self._lease_watch())

    def lease_undepend(self, src: str, ctrl) -> None:
        deps = self._lease_deps.get(src)
        if deps is None:
            return
        deps.discard(ctrl)
        if not deps:
            del self._lease_deps[src]

    async def _lease_watch(self) -> None:
        """Wake EXACTLY the groups depending on an expired store lease,
        each with a randomized election timeout (no thundering herd).
        Sleeps until the earliest expiry (deadlines only ever extend;
        lease_depend nudges us when a new dependency might be earlier)
        — a fully-hibernated process takes no standing sub-second
        wakeups from the watcher."""
        try:
            while self._lease_deps:
                horizon = min(self._lease_from.get(src, 0.0)
                              for src in self._lease_deps)
                wait = max(0.02, horizon - self.clock.monotonic())
                self._lease_watch_nudge.clear()
                try:
                    await asyncio.wait_for(
                        self._lease_watch_nudge.wait(), wait)
                except asyncio.TimeoutError:
                    pass
                now = self.clock.monotonic()
                for src in [s for s in list(self._lease_deps)
                            if self._lease_from.get(s, 0.0) <= now]:
                    ctrls = self._lease_deps.pop(src, set())
                    self.lease_expiries += 1
                    LOG.info("store lease from %s expired: waking %d "
                             "quiescent groups", src, len(ctrls))
                    for ctrl in ctrls:
                        try:
                            ctrl.wake_for_lease_expiry()
                        except Exception:  # noqa: BLE001 — one group's
                            LOG.exception("lease-expiry wake failed")
        except asyncio.CancelledError:
            return
        finally:
            self._lease_watch_task = None

    async def _loop(self) -> None:
        try:
            while True:
                # widened sleep: load_widen_max caps at the classic
                # per-group cadence (see ctor), so stretching under row
                # load never risks follower election timeouts
                await asyncio.sleep(self._interval_s * self._widen)
                await self.tick_once()
        except asyncio.CancelledError:
            return

    async def tick_once(self) -> None:
        self.pulse(list(self._members.values()))

    def pulse(self, replicators: list["Replicator"]) -> None:
        """Beat the given replicators NOW, batched per destination
        endpoint.  Two callers: the hub's own clock (tick_once) and the
        engine's hb_due mask (MultiRaftEngine._flush_heartbeats), which
        passes every due group's replicators in one call so idle beats
        stay O(endpoints) per tick.

        Steady-state beats ride the beat-plane FAST path (CompactBeat
        data, inline lock-free validation on the receiver — see
        NodeManager._handle_multi_beat_fast): at region density the
        classic per-beat handler fan-out is the dominant idle CPU burn.
        A group whose fast beat answers needs-full (term moved,
        committed behind, follower restarted) gets a classic
        full-semantics beat as the follow-up; replicators not yet
        matched, or whose endpoint hasn't advertised the capability,
        take the classic path directly.

        Frames/beats MUST be built here, synchronously: between the
        is_leader() check and an await, a step-down + re-election can
        change the node's term, and a beat built late would claim
        leadership of the NEW term from a node that is now a follower
        (observed as spurious "two leaders in one term" conflicts on
        receivers).  No awaits may separate the check from the build."""
        by_dst_fast: dict[str, list[tuple["Replicator", CompactBeat]]] = {}
        classic: list["Replicator"] = []
        for r in replicators:
            node = r._node
            if not node.is_leader() or not r._running:
                continue
            quiesce_ms = getattr(r, "_quiesce_lease_ms", 0)
            if quiesce_ms:
                r._quiesce_lease_ms = 0
            if (r.peer_multi_hb and r._matched
                    and self._fast_ok.get(r.peer.endpoint, True)):
                committed = min(node.ballot_box.last_committed_index,
                                r.match_index)
                # idle-burn dominator at region density: reuse the beat
                # object while (term, committed) are unchanged — the
                # steady state — instead of rebuilding it every pulse
                cached = getattr(r, "_fast_beat_cache", None)
                if quiesce_ms:
                    # quiesce handshake rides its own (uncached) beat
                    beat = CompactBeat(
                        group_id=node.group_id,
                        server_id=str(node.server_id),
                        peer_id=str(r.peer),
                        term=node.current_term,
                        committed_index=committed,
                        quiesce=True, lease_ms=quiesce_ms)
                elif (cached is not None
                        and cached.term == node.current_term
                        and cached.committed_index == committed):
                    beat = cached
                else:
                    beat = CompactBeat(
                        group_id=node.group_id,
                        server_id=str(node.server_id),
                        peer_id=str(r.peer),
                        term=node.current_term,
                        committed_index=committed)
                    r._fast_beat_cache = beat
                by_dst_fast.setdefault(r.peer.endpoint, []).append((r, beat))
                continue
            if quiesce_ms:
                # the handshake needs the fast path; a classic-only peer
                # cannot carry it — the group just stays active
                ctrl = getattr(node, "_ctrl", None)
                if ctrl is not None and hasattr(ctrl, "abort_quiesce"):
                    ctrl.abort_quiesce()
            classic.append(r)
        # fold this pulse's row count into the cadence-widening EMA: a
        # hub carrying load_widen_rows+ rows per pulse converges on
        # load_widen_max x its base interval (timer-mode standing-load
        # relief at region density); an idling hub decays back to 1.0
        rows = sum(map(len, by_dst_fast.values())) + len(classic)
        target = 1.0 + (min(1.0, rows / self.load_widen_rows)
                        * (self.load_widen_max - 1.0))
        self._widen += 0.25 * (target - self._widen)
        if self._widen > 1.05:
            self.widened_pulses += 1
        for dst, pairs in by_dst_fast.items():
            for ci in range(0, len(pairs), self.max_fast_beats_per_rpc):
                chunk = pairs[ci:ci + self.max_fast_beats_per_rpc]
                key = f"fast:{dst}#{ci // self.max_fast_beats_per_rpc}"
                if key in self._inflight:
                    continue
                t = asyncio.ensure_future(self._beat_fast(dst, chunk))
                self._inflight[key] = t
                reps = [r for r, _ in chunk]
                t.add_done_callback(
                    lambda _t, k=key, rs=reps: self._reap(k, _t, rs))
        if classic:
            self._pulse_classic(classic)

    def _reap(self, key: str, t: asyncio.Task,
              fallback: Optional[list["Replicator"]] = None) -> None:
        """Done-callback for beat tasks: always retrieve the exception
        (an unretrieved one is event-loop log spam AND a silently
        missed beat), and give fast-path chunks that died on an
        unexpected error their classic-beat fallback so a persistent
        non-RpcError (e.g. codec failure) can't starve those groups of
        heartbeats until their followers start elections."""
        self._inflight.pop(key, None)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is None:
            return
        LOG.warning("heartbeat batch %s failed: %r", key, exc)
        if fallback:
            self._abort_quiesce(fallback)
            self.fast_fallbacks += len(fallback)
            self._pulse_classic([r for r in fallback if r._running])

    def _dispatch_classic(
            self, by_dst: dict[str, list[tuple["Replicator", bytes]]]
    ) -> None:
        # fire-and-track per destination chunk: the tick cadence must NOT
        # wait for RPC round trips (a slow endpoint would stall
        # heartbeats to every other endpoint and trigger elections
        # everywhere), and batches are capped so one contended group's
        # slow ack only couples the fates of its own chunk, not every
        # group on the endpoint pair.  A chunk whose previous RPC is
        # still in flight is skipped this tick.
        for dst, pairs in by_dst.items():
            for ci in range(0, len(pairs), self.max_beats_per_rpc):
                chunk = pairs[ci:ci + self.max_beats_per_rpc]
                key = f"{dst}#{ci // self.max_beats_per_rpc}"
                if key in self._inflight:
                    continue
                t = asyncio.ensure_future(self._beat_endpoint(dst, chunk))
                self._inflight[key] = t
                t.add_done_callback(
                    lambda _t, k=key: self._reap(k, _t))

    @staticmethod
    def _abort_quiesce(reps: list["Replicator"]) -> None:
        """A chunk carrying quiesce-handshake beats failed (RPC error,
        short response, classic fallback): the affected groups stay
        active — a hibernation the followers may not have joined is a
        safety hole, an aborted one just costs beats."""
        for r in reps:
            ctrl = getattr(r._node, "_ctrl", None)
            if ctrl is not None and hasattr(ctrl, "abort_quiesce"):
                ctrl.abort_quiesce()

    async def _beat_fast(self, dst: str,
                         pairs: list[tuple["Replicator", object]]) -> None:
        reps = [r for r, _ in pairs]
        items = [b for _, b in pairs]
        quiescing = [r for r, b in pairs if getattr(b, "quiesce", False)]
        node = reps[0]._node
        self.rpcs_sent += 1
        self.fast_beats_sent += len(items)
        t0 = self.clock.monotonic()
        try:
            resp = await node.transport.call(
                dst, "multi_beat_fast", BatchRequest(items=items),
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError as e:
            self._abort_quiesce(quiescing)
            if is_no_method(e):
                # receiver predates the beat plane: classic beats only
                self._fast_ok[dst] = False
                self.fast_fallbacks += len(reps)
                self.pulse(reps)
            return  # else: silence — dead-node detection, as direct
        if self.health is not None:
            self.health.note_peer_rtt(dst, self.clock.monotonic() - t0)
        if len(resp.items) != len(items):
            # short/overlong response: zip would silently drop trailing
            # replicators' acks — treat the whole chunk as deviating
            LOG.warning("multi_beat_fast %s: %d acks for %d beats",
                        dst, len(resp.items), len(items))
            self._abort_quiesce(quiescing)
            self.fast_fallbacks += len(reps)
            self._pulse_classic(reps)
            return
        now = self.clock.monotonic()
        if resp.items:
            self._note_peer_clock(dst, resp.items[0], t0, now)
        fallback: list["Replicator"] = []
        for (r, beat), ack in zip(pairs, resp.items):
            if not r._running or not r._node.is_leader():
                continue
            proposed = getattr(beat, "quiesce", False)
            if getattr(ack, "ok", False):
                # inline ack bookkeeping: the lease plane only needs the
                # (peer, when) write — no per-ack task, no node lock
                r.last_rpc_ack = now
                r._node.on_peer_ack(r.peer, now)
                if proposed:
                    ctrl = getattr(r._node, "_ctrl", None)
                    if ctrl is not None and \
                            hasattr(ctrl, "note_quiesce_ack"):
                        ctrl.note_quiesce_ack(r.peer)
            else:
                if proposed:
                    self._abort_quiesce([r])
                fallback.append(r)
        if fallback:
            # full-semantics follow-up for just the deviating groups
            # (term moved / committed behind / follower restarted)
            self.fast_fallbacks += len(fallback)
            self._pulse_classic(fallback)

    def _pulse_classic(self, replicators: list["Replicator"]) -> None:
        """Classic framed beats only (no fast-path retry) — used for
        fast-beat fallbacks to avoid ping-ponging."""
        by_dst: dict[str, list[tuple["Replicator", bytes]]] = {}
        for r in replicators:
            node = r._node
            if not node.is_leader() or not r._running:
                continue
            frame = encode_message(r.build_heartbeat_request())
            by_dst.setdefault(r.peer.endpoint, []).append((r, frame))
        self._dispatch_classic(by_dst)

    async def _beat_endpoint(self, dst: str,
                             pairs: list[tuple["Replicator", bytes]]
                             ) -> None:
        reps = [r for r, _ in pairs]
        frames = [f for _, f in pairs]
        # any member's transport works; they share the process endpoint
        node = reps[0]._node
        self.rpcs_sent += 1
        self.beats_sent += len(frames)
        t0 = self.clock.monotonic()
        try:
            # half-election-timeout budget, like the direct heartbeat
            # path: with the inflight-chunk skip, a lost request must
            # release its chunk quickly or one dropped packet silences
            # up to max_beats_per_rpc groups for a full timeout
            resp: MultiHeartbeatResponse = await node.transport.call(
                dst, "multi_heartbeat",
                MultiHeartbeatRequest(beats=frames),
                timeout_ms=node.options.election_timeout_ms // 2 or 1)
        except RpcError:
            return  # no acks: dead-node detection sees silence, as direct
        if self.health is not None:
            self.health.note_peer_rtt(dst, self.clock.monotonic() - t0)
        if len(resp.acks) != len(frames):
            # a short ack list must read as silence for the WHOLE chunk
            # (dead-node detection semantics), not as acks for whichever
            # prefix zip happens to pair up
            LOG.warning("multi_heartbeat %s: %d acks for %d beats",
                        dst, len(resp.acks), len(frames))
            return
        for r, blob in zip(reps, resp.acks):
            try:
                ack = decode_message(blob)
            except Exception:  # noqa: BLE001 — malformed single ack
                continue
            if not hasattr(ack, "success"):
                continue  # ErrorResponse: that group was unserviceable
            if r._running and r._node.is_leader():
                await r.process_heartbeat_response(ack)
