"""SnapshotExecutor: periodic/on-demand snapshot save, remote install.

Reference parity: ``core:storage/snapshot/SnapshotExecutorImpl``
(SURVEY.md §3.1): doSnapshot (FSM save -> atomic commit -> log prefix
truncation), installSnapshot (leader streams files to a lagging follower
via the file service; follower loads and resets its log).  This subsystem
doubles as checkpoint/resume AND log compaction (§6).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

from tpuraft.conf import Configuration, ConfigurationEntry
from tpuraft.entity import LogId, PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.rpc.messages import (
    InstallSnapshotRequest,
    InstallSnapshotResponse,
    SnapshotMeta,
)
from tpuraft.rpc.transport import RpcError
from tpuraft.storage.log_manager import _is_enospc
from tpuraft.storage.snapshot import (
    LocalSnapshotStorage,
    RemoteFileCopier,
    SnapshotReader,
    ThroughputSnapshotThrottle,
    _MANIFEST,
    _decode_manifest,
)

LOG = logging.getLogger(__name__)


class SnapshotExecutor:
    def __init__(self, node, snapshot_uri: str):
        assert snapshot_uri.startswith("file://"), snapshot_uri
        self._node = node
        self._storage = LocalSnapshotStorage(snapshot_uri[len("file://"):])
        self.last_snapshot_id = LogId(0, 0)
        self.installing = False
        self._saving = False
        # one throttle for the whole node so concurrent installs share the
        # byte budget; rebuilt if the configured rate changes
        self._throttle: Optional[ThroughputSnapshotThrottle] = None
        self._throttle_bps = 0

    def _get_throttle(self) -> Optional[ThroughputSnapshotThrottle]:
        bps = self._node.options.snapshot.throttle_bytes_per_sec
        if bps != self._throttle_bps:
            self._throttle = ThroughputSnapshotThrottle(bps) if bps > 0 else None
            self._throttle_bps = bps
        return self._throttle

    # -- lifecycle -----------------------------------------------------------

    async def init(self) -> LogId:
        """Load the newest local snapshot into the FSM (direct call — the
        FSMCaller loop isn't running yet at bootstrap). Returns the
        bootstrap id the FSM state corresponds to."""
        self._storage.init()
        reader = self._storage.open()
        if reader is None:
            return LogId(0, 0)
        meta = reader.load_meta()
        node = self._node
        ok = await node.options.fsm.on_snapshot_load(reader)
        if not ok:
            LOG.error("%s: on_snapshot_load failed at init", node)
            return LogId(0, 0)
        self.last_snapshot_id = LogId(meta.last_included_index,
                                      meta.last_included_term)
        conf = _conf_from_meta(meta)
        await node.log_manager.set_snapshot(
            self.last_snapshot_id, conf,
            keep_margin=node.options.snapshot.log_index_margin)
        node.conf_entry = conf
        return self.last_snapshot_id

    async def shutdown(self) -> None:
        pass

    # -- save ----------------------------------------------------------------

    async def do_snapshot(self) -> Status:
        node = self._node
        if self.installing:
            return Status.error(RaftError.EBUSY, "installing a snapshot")
        if self._saving:
            return Status.error(RaftError.EBUSY, "snapshot already running")
        if node.fsm_caller.last_applied_index <= self.last_snapshot_id.index:
            return Status.error(RaftError.ECANCELED, "nothing new to snapshot")
        self._saving = True
        try:
            writer = self._storage.create()
            done_fut: asyncio.Future = asyncio.get_running_loop().create_future()
            meta_box: dict = {}

            def done(st: Status) -> None:
                if not done_fut.done():
                    done_fut.set_result(st)

            # capture applied state consistently: build meta inside the
            # FSMCaller queue right before on_snapshot_save runs
            async def save_wrapper(w, d):
                meta_box["id"] = LogId(node.fsm_caller.last_applied_index,
                                       node.fsm_caller.last_applied_term)
                try:
                    await node.options.fsm.on_snapshot_save(w, d)
                except Exception as exc:
                    # a failed SAVE (ENOSPC on the temp dir, most
                    # likely) must not escape into the FSMCaller drain
                    # loop — that poisons the queue and ERRORs the
                    # whole node.  The old snapshot is untouched; fail
                    # just this attempt and let reclaim retry.
                    LOG.exception("%s snapshot save failed", node)
                    d(Status.error(RaftError.EIO,
                                   f"snapshot save failed: {exc}"))

            node.fsm_caller._enqueue(
                ("snapshot_save_custom", (writer, done, save_wrapper)))
            st = await done_fut
            if not st.is_ok():
                return st
            snap_id: LogId = meta_box["id"]
            conf_entry = node.log_manager.conf_manager.get(snap_id.index)
            if conf_entry.conf.is_empty():
                conf_entry = ConfigurationEntry(
                    LogId(0, 0), node.conf_entry.conf.copy(),
                    node.conf_entry.old_conf.copy())
            meta = SnapshotMeta(
                last_included_index=snap_id.index,
                last_included_term=snap_id.term,
                peers=[str(p) for p in conf_entry.conf.peers],
                old_peers=[str(p) for p in conf_entry.old_conf.peers],
                learners=[str(p) for p in conf_entry.conf.learners],
                old_learners=[str(p) for p in conf_entry.old_conf.learners],
                witnesses=[str(p) for p in conf_entry.conf.witnesses],
                old_witnesses=[str(p) for p in conf_entry.old_conf.witnesses],
            )
            loop = asyncio.get_running_loop()
            budget = getattr(node.options, "disk_budget", None)
            try:
                await loop.run_in_executor(
                    None, self._storage.commit, writer, meta)
            except Exception as exc:
                # commit failed (ENOSPC on manifest write / rename):
                # the previous snapshot_<N> is intact and the temp dir
                # is swept at next init/create — report, don't crash
                LOG.exception("%s snapshot commit failed", node)
                if budget is not None and _is_enospc(exc):
                    budget.note_enospc()
                return Status.error(RaftError.EIO,
                                    f"snapshot commit failed: {exc}")
            if budget is not None:
                budget.note_snapshot(self._storage.last_commit_bytes
                                     - self._storage.last_reclaimed_bytes)
            self.last_snapshot_id = snap_id
            await node.log_manager.set_snapshot(
                snap_id, conf_entry,
                keep_margin=node.options.snapshot.log_index_margin)
            node.metrics.counter("snapshots-saved")
            LOG.info("%s snapshot saved at %s", node, snap_id)
            return Status.OK()
        finally:
            self._saving = False

    # -- leader: install on a lagging follower -------------------------------

    async def send_install_snapshot(self, peer: PeerId, replicator) -> bool:
        node = self._node
        reader = self._storage.open()
        if reader is None:
            LOG.error("%s: follower %s needs snapshot but none exists",
                      node, peer)
            return False
        meta = reader.load_meta()
        if meta.last_included_index < replicator.next_index:
            return False  # snapshot too old to help
        reader_id = node.node_manager.register_file_reader(
            _ChunkAdapter(reader, self._get_throttle()))
        try:
            req = InstallSnapshotRequest(
                group_id=node.group_id,
                server_id=str(node.server_id),
                peer_id=str(peer),
                term=node.current_term,
                meta=meta,
                uri=f"remote://{node.server_id.endpoint}/{reader_id}",
            )
            # the RPC stays open for the whole file copy: under a byte
            # throttle that takes total_size/bps, so scale the timeout
            # (2x for contention with other installs sharing the budget)
            timeout_ms = node.options.election_timeout_ms * 10
            if self._throttle is not None:
                timeout_ms += int(
                    reader.total_size() / self._throttle_bps * 2000)
            try:
                resp: InstallSnapshotResponse = await node.transport.install_snapshot(
                    peer.endpoint, req, timeout_ms=timeout_ms)
            except RpcError as e:
                LOG.warning("%s install_snapshot to %s failed: %s", node, peer, e)
                return False
            if resp.term > node.current_term:
                await node.step_down_on_higher_term(
                    resp.term, f"install_snapshot response from {peer}")
                return False
            if not resp.success:
                return False
            replicator.next_index = meta.last_included_index + 1
            replicator._matched = False  # re-probe from the snapshot point
            node.metrics.counter("install-snapshot-sent")
            LOG.info("%s installed snapshot %d on %s", node,
                     meta.last_included_index, peer)
            return True
        finally:
            node.node_manager.unregister_file_reader(reader_id)

    # -- follower: receive an install ---------------------------------------

    async def handle_install_snapshot(self, req: InstallSnapshotRequest
                                      ) -> InstallSnapshotResponse:
        node = self._node
        async with node._lock:
            if req.term < node.current_term:
                return InstallSnapshotResponse(term=node.current_term,
                                               success=False)
            from tpuraft.core.node import State

            if req.term > node.current_term or node.state != State.FOLLOWER:
                await node._step_down(req.term, Status.error(
                    RaftError.EHIGHERTERMREQUEST, "install_snapshot"),
                    new_leader=PeerId.parse(req.server_id))
            node._last_leader_timestamp = node._clock.monotonic()
            if self.installing or self._saving:
                # save and install share the storage temp dir — mutual
                # exclusion both ways (reference: savingSnapshot /
                # downloadingSnapshot guards); the leader's paced retry
                # comes back after the local save finishes
                return InstallSnapshotResponse(term=node.current_term,
                                               success=False)
            if req.meta.last_included_index <= self.last_snapshot_id.index:
                return InstallSnapshotResponse(term=node.current_term,
                                               success=True)
            self.installing = True
        try:
            ok = await self._do_install(req)
            return InstallSnapshotResponse(term=node.current_term, success=ok)
        finally:
            self.installing = False

    async def _load_committed_install(self, meta: SnapshotMeta,
                                      path: str) -> bool:
        """Shared tail of BOTH install paths (full file copy and the
        witness meta-only skip): load the committed snapshot dir into
        the FSM queue, then adopt id/conf/commit under the node lock —
        one copy of the state-mutation protocol, so a future change
        cannot drift between the two."""
        node = self._node
        reader = SnapshotReader(path)
        fut = await node.fsm_caller.on_snapshot_load(reader)
        if not await fut:
            LOG.error("%s on_snapshot_load failed during install", node)
            return False
        snap_id = LogId(meta.last_included_index, meta.last_included_term)
        self.last_snapshot_id = snap_id
        conf = _conf_from_meta(meta)
        async with node._lock:
            await node.log_manager.set_snapshot(snap_id, conf)
            node.conf_entry = conf
            node.ballot_box.update_conf(conf.conf, conf.old_conf)
            node.ballot_box.set_last_committed_index(snap_id.index)
        node.metrics.counter("install-snapshot-received")
        LOG.info("%s loaded installed snapshot at %s", node, snap_id)
        return True

    async def _do_install(self, req: InstallSnapshotRequest) -> bool:
        node = self._node
        loop = asyncio.get_running_loop()
        if node.options.witness:
            # WITNESS SKIP: a witness holds no FSM state, so there is
            # nothing to download — commit an EMPTY local snapshot at
            # the leader's meta (the compaction point + conf), load it
            # through the null FSM (advances the applied index), and
            # reset the metadata journal there.  A lagging geo witness
            # catches up in one meta-sized RPC instead of a full state
            # transfer over the WAN.
            writer = self._storage.create()
            path = await loop.run_in_executor(
                None, self._storage.commit, writer, req.meta)
            node.metrics.counter("install-snapshot-witness-skips")
            return await self._load_committed_install(req.meta, path)
        # parse uri: remote://<endpoint>/<reader_id>
        rest = req.uri[len("remote://"):]
        endpoint, _, rid = rest.partition("/")
        copier = RemoteFileCopier(node.transport, endpoint, int(rid),
                                  chunk_size=node.options.snapshot.max_chunk_size)
        writer = self._storage.create()
        try:
            manifest_blob = await copier.read_bytes(_MANIFEST)
            meta, files = _decode_manifest(manifest_blob)
            # filter-before-copy (reference: LocalSnapshotCopier#filter
            # BeforeCopy): files our latest local snapshot already holds
            # with identical name+size+crc are copied locally, not
            # re-downloaded — an InstallSnapshot where only part of the
            # state changed ships only the changed files
            local = self._storage.open()
            have = {}
            if local is not None:
                have = {(lf.name, lf.size, lf.crc):
                        os.path.join(local.path, lf.name)
                        for lf in local.files()}
            reused = 0
            loop = asyncio.get_running_loop()
            for f in files:
                dst = os.path.join(writer.path, f.name)
                if (f.name, f.size, f.crc) in have and local is not None:
                    # verify the LOCAL bytes before trusting them: the
                    # manifest crc was recorded at save time; rot since
                    # then must fall back to the network copy, not be
                    # laundered into a new self-consistent manifest
                    ok = await loop.run_in_executor(
                        None, _reuse_local_file, local, f.name, dst)
                    if ok:
                        reused += 1
                    else:
                        await copier.copy_to(f.name, dst)
                else:
                    await copier.copy_to(f.name, dst)
                writer.add_file(f.name)
            if reused:
                node.metrics.counter("install-snapshot-files-reused", reused)
                LOG.info("%s install: reused %d/%d files from local snapshot",
                         node, reused, len(files))
        except (RpcError, ValueError, IOError) as e:
            LOG.warning("%s snapshot copy failed: %s", node, e)
            return False
        path = await loop.run_in_executor(
            None, self._storage.commit, writer, meta)
        return await self._load_committed_install(meta, path)


class _ChunkAdapter:
    """Adapts SnapshotReader to the file-service read_file(name, off, count)
    protocol (reference: FileService + SnapshotFileReader).  ``throttle``
    (if set) is consulted by the file service before each chunk read."""

    def __init__(self, reader: SnapshotReader,
                 throttle: Optional[ThroughputSnapshotThrottle] = None):
        self._reader = reader
        self.throttle = throttle

    def read_file(self, name: str, offset: int, count: int):
        return self._reader.read_chunk(name, offset, count)


def _reuse_local_file(local, name: str, dst: str) -> bool:
    """Copy a file from the local snapshot into ``dst`` with the same
    durability as a network download: crc-verified read (read_file
    raises on rot), then write + fsync.  False => caller re-downloads.
    Runs in an executor thread."""
    try:
        data = local.read_file(name)
    except IOError:
        return False
    if data is None:
        return False
    with open(dst, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return True


def _conf_from_meta(meta: SnapshotMeta) -> ConfigurationEntry:
    return ConfigurationEntry(
        id=LogId(meta.last_included_index, meta.last_included_term),
        conf=Configuration(
            [PeerId.parse(p) for p in meta.peers],
            [PeerId.parse(p) for p in meta.learners],
            [PeerId.parse(p) for p in meta.witnesses]),
        old_conf=Configuration(
            [PeerId.parse(p) for p in meta.old_peers],
            [PeerId.parse(p) for p in meta.old_learners],
            [PeerId.parse(p) for p in meta.old_witnesses]),
    )
