"""AppendBatcher: store-wide append rounds — the write-plane mirror of
the read plane's ReadConfirmBatcher.

The send plane (``EndpointSender``) already packs many groups' append
frames into one ``multi_append`` RPC, but its append lane is strict
stop-and-wait per destination: ONE RPC in flight per endpoint pair, so
at region density every led group's window convoys behind whichever
chunk currently holds the lane (receiver-side fsync included).  The
read plane escaped exactly this shape in PR 10 by windowing store-wide
rounds; this batcher does the same for entries:

- Each drain pass collects EVERY pending (group, peer) window headed
  for one destination endpoint and ships them as ONE ``store_append``
  RPC (``StoreAppendRequest`` rows = plain AppendEntriesRequests — the
  per-group prev-log/term semantics are untouched, so safety is
  per-group unchanged).
- Rounds are WINDOWED per destination (``max_inflight_rounds``): up to
  that many store-wide RPCs ride one endpoint pair concurrently, so a
  slow chunk (one group's big fsync) no longer serializes every other
  group's tail latency behind it.  Per-group ordering still holds with
  concurrent rounds because a replicator submits at most ONE window at
  a time (``Replicator._pending``) — a group's frames can never ride
  two in-flight rounds, which is the whole in-order contract the
  receiver needs.
- One dead endpoint's round times out on its own lane; other
  destinations' lanes never queue behind it (the windowing bound
  tests/test_append_batch.py pins down).
- A receiver that predates ``store_append`` answers ENOMETHOD and this
  endpoint downgrades PERMANENTLY to classic per-group
  ``append_entries`` RPCs (``send_plane.sequential_appends`` — the PD
  delta-batch / kv_batch mixed-fleet pattern), counted in
  ``fallbacks``/``legacy_rows``.

Ack resolution rides the existing ``Replicator.on_batch_responses``
contract, so step-down/term pinning, fast backoff, rollback and the
commit tally (``on_match_advanced`` → ballot box, which for
engine-backed nodes now closes quorums eagerly on the ack — see
``TpuBallotBox.commit_at``) are one implementation shared with the
legacy path.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from tpuraft.rpc.messages import ErrorResponse, StoreAppendRequest
from tpuraft.rpc.transport import RpcError, is_no_method
from tpuraft.util import clock as clockmod
from tpuraft.util.metrics import MetricRegistry

LOG = logging.getLogger(__name__)


def _consume(t: "asyncio.Task") -> None:
    if not t.cancelled():
        t.exception()


# graftcheck: loop-confined — one batcher per store process, driven from
# the store's event loop (replicator submits + round tasks); the lane
# dicts and counters are lockless by that confinement
class AppendBatcher:
    """Windowed store-wide append rounds, one lane per destination.

    Replicators submit through the same ``submit_append(rep, reqs)``
    surface as ``EndpointSender``; the batcher groups everything
    pending per destination on the next loop pass (a burst of
    same-iteration applies coalesces into one round) and keeps up to
    ``max_inflight_rounds`` RPCs in flight per lane.
    """

    max_inflight_rounds = 4
    # cap per round RPC: bounds the receiver's fan-out burst (each row
    # may carry entries + a disk flush) — the EndpointSender chunk size
    max_rows_per_round = 128

    def __init__(self) -> None:
        # dst endpoint -> [(replicator, [AppendEntriesRequest], tmo_ms)]
        self._pending: dict[str, list] = {}
        self._inflight: dict[str, set] = {}
        self._kick_scheduled: set[str] = set()
        self._fast_ok: dict[str, bool] = {}  # dst serves store_append
        self._shut = False
        # gray-failure signal sink (HealthTracker): every round's RPC
        # doubles as a per-endpoint RTT probe
        self.health = None
        # store clock (ISSUE 18): the owning StoreEngine re-points this
        # so the RTT probes ride the store's time plane
        self.clock = clockmod.SYSTEM
        # counters (describe() + MetricRegistry + bench/soak stats)
        self.rounds = 0          # store_append RPCs sent
        self.rows = 0            # (group, peer) frames carried
        self.entries = 0         # log entries carried inside them
        self.fallbacks = 0       # ENOMETHOD downgrades (per endpoint)
        self.legacy_rows = 0     # frames shipped per-group post-downgrade
        self.deviating_rows = 0  # rows answered ErrorResponse (busy/absent)
        self.rejected_rows = 0   # in-protocol rejections (prev-log mismatch)
        self.round_errors = 0    # whole-RPC failures (timeout/unreachable)
        # gauges bound to the live counters (the ReadConfirmBatcher idiom)
        self.metrics = MetricRegistry()
        for name in ("rounds", "rows", "entries", "fallbacks",
                     "legacy_rows", "deviating_rows", "rejected_rows",
                     "round_errors"):
            self.metrics.gauge(f"append_batcher.{name}",
                               lambda n=name: getattr(self, n))
        self.metrics.gauge(
            "append_batcher.rows_per_round",
            lambda: self.rows / self.rounds if self.rounds else 0.0)

    # -- observability --------------------------------------------------------

    def counters(self) -> dict:
        return {
            "append_rounds": self.rounds,
            "append_rows": self.rows,
            "append_entries_batched": self.entries,
            "append_fallbacks": self.fallbacks,
            "append_legacy_rows": self.legacy_rows,
            "append_deviating_rows": self.deviating_rows,
            "append_rejected_rows": self.rejected_rows,
            "append_round_errors": self.round_errors,
        }

    def describe(self) -> str:
        amort = self.rows / self.rounds if self.rounds else 0.0
        return (f"AppendBatcher<rounds={self.rounds} rows={self.rows} "
                f"rows_per_round={amort:.2f} entries={self.entries} "
                f"fallbacks={self.fallbacks} legacy={self.legacy_rows} "
                f"deviating={self.deviating_rows} "
                f"rejected={self.rejected_rows} "
                f"errors={self.round_errors}>")

    # -- submit ---------------------------------------------------------------

    def submit_append(self, replicator, reqs: list) -> None:
        """Queue one group's window for its peer's endpoint lane.  Same
        contract as EndpointSender.submit_append: the whole window
        resolves through ``replicator.on_batch_responses`` /
        ``on_batch_error``, in send order."""
        node = replicator._node
        dst = replicator.peer.endpoint
        if self._shut:
            self._spawn(replicator.on_batch_error())
            return
        self._pending.setdefault(dst, []).append(
            (replicator, reqs, node.options.election_timeout_ms))
        if dst not in self._kick_scheduled:
            # next-loop-pass kick: every window submitted by tasks
            # runnable this iteration (a burst of concurrent applies)
            # joins the same round
            self._kick_scheduled.add(dst)
            asyncio.get_running_loop().call_soon(self._kick, dst)

    def _kick(self, dst: str) -> None:
        self._kick_scheduled.discard(dst)
        if self._shut:
            return
        pend = self._pending.get(dst)
        if not pend:
            return
        inflight = self._inflight.setdefault(dst, set())
        while pend and len(inflight) < self.max_inflight_rounds:
            # take whole windows until the row cap (a window never
            # straddles rounds: its acks resolve as one unit)
            batch: list = []
            nrows = 0
            while pend and (not batch
                            or nrows + len(pend[0][1])
                            <= self.max_rows_per_round):
                item = pend.pop(0)
                batch.append(item)
                nrows += len(item[1])
            t = asyncio.ensure_future(self._round(dst, batch))
            inflight.add(t)

            def _done(tt, dst=dst):
                self._inflight[dst].discard(tt)
                if not tt.cancelled() and tt.exception() is not None:
                    LOG.warning("append round to %s failed: %r", dst,
                                tt.exception())
                self._kick(dst)  # free slot: drain what queued meanwhile

            t.add_done_callback(_done)

    @staticmethod
    def _spawn(coro) -> None:
        t = asyncio.ensure_future(coro)
        t.add_done_callback(_consume)

    # -- rounds ---------------------------------------------------------------

    async def _round(self, dst: str, batch: list) -> None:
        if not self._fast_ok.get(dst, True):
            await self._legacy_round(dst, batch)
            return
        rows: list = []
        routes: list = []           # (replicator, frame count)
        timeout_ms = 0.0
        for rep, reqs, tmo in batch:
            rows.extend(reqs)
            routes.append((rep, len(reqs)))
            # groups with different election timeouts share the round:
            # budget for the slowest (the EndpointSender rule)
            timeout_ms = max(timeout_ms, tmo)
        transport = batch[0][0]._node.transport
        self.rounds += 1
        self.rows += len(rows)
        self.entries += sum(len(r.entries) for r in rows)
        t0 = self.clock.monotonic()
        try:
            resp = await transport.call(
                dst, "store_append", StoreAppendRequest(rows=rows),
                timeout_ms=timeout_ms)
        except asyncio.CancelledError:
            # shutdown mid-RPC: nothing was dispatched yet — fail the
            # whole batch so no replicator stays _pending forever
            self._fail_batch(batch)
            raise
        except RpcError as e:
            if is_no_method(e):
                # receiver predates the write-plane batcher: resend
                # these per group and stay legacy for this endpoint
                self._fast_ok[dst] = False
                self.fallbacks += 1
                await self._legacy_round(dst, batch)
                return
            self.round_errors += 1
            self._fail_batch(batch)
            return
        except Exception:  # noqa: BLE001 — a round bug must not silence
            LOG.exception("store_append round to %s crashed", dst)
            self.round_errors += 1
            self._fail_batch(batch)
            return
        if self.health is not None:
            self.health.note_peer_rtt(dst, self.clock.monotonic() - t0)
        acks = resp.acks
        if len(acks) != len(rows):
            # short/overlong reply reads as failure for the whole round
            # (zip would pair acks with the wrong groups' frames)
            LOG.warning("store_append %s: %d acks for %d rows", dst,
                        len(acks), len(rows))
            self.round_errors += 1
            self._fail_batch(batch)
            return
        i = 0
        for rep, count in routes:
            chunk = acks[i:i + count]
            i += count
            for a in chunk:
                if isinstance(a, ErrorResponse):
                    self.deviating_rows += 1
                elif not getattr(a, "success", True):
                    self.rejected_rows += 1
            # per-group resolution (term pinning, rollback, fast
            # backoff) — the one implementation both planes share.
            # Awaited INLINE in the round task, not spawned: one task
            # per group per round was a measurable slice of the
            # saturated loop at region density, resolutions are short
            # (ack bookkeeping + a wake), and a round that awaits its
            # own groups' resolutions is exactly the backpressure the
            # window wants.
            try:
                await rep.on_batch_responses(chunk)
            except Exception:  # noqa: BLE001 — one group's resolution
                LOG.exception("append-round resolution failed")

    async def _legacy_round(self, dst: str, batch: list) -> None:
        """Per-group classic append_entries for pre-batcher receivers.
        Groups run concurrently (their flushes still coalesce into the
        receiver's group-commit); the round slot stays occupied until
        all resolve, which keeps stop-and-wait-ish backpressure toward
        the old endpoint."""
        from tpuraft.core.send_plane import sequential_appends

        self.legacy_rows += sum(len(reqs) for _rep, reqs, _t in batch)
        await asyncio.gather(
            *(sequential_appends(rep, dst, reqs)
              for rep, reqs, _tmo in batch),
            return_exceptions=True)

    def _fail_batch(self, batch: list) -> None:
        for rep, _reqs, _tmo in batch:
            self._spawn(rep.on_batch_error())

    # -- lifecycle ------------------------------------------------------------

    async def shutdown(self) -> None:
        self._shut = True
        for pend in self._pending.values():
            self._fail_batch(pend)
            pend.clear()
        for tasks in self._inflight.values():
            for t in list(tasks):
                t.cancel()
        self._pending.clear()
