"""SendPlane: one batched sender per destination endpoint.

The TPU-native answer to the reference's per-(group, peer) sender
threads (``core:Replicator`` posting to shared ``Utils.cpus()``
executors — SURVEY.md §3.5 "Replication pipelining", §8.2 "the host
applies device outputs (send-plans)"): with thousands of raft groups
multiplexed on a handful of process endpoints, per-group vote fanouts
and per-(group, peer) replication tasks cost O(G x P) standing asyncio
tasks — the measured 16K-group election-starvation wall
(BENCH_SCALE.json r3).  Here every protocol send targeting one endpoint
is enqueued to that endpoint's :class:`EndpointSender`, whose single
drain task packs everything pending into ONE ``multi_append`` /
``multi_vote`` RPC (a :class:`~tpuraft.rpc.messages.BatchRequest`) per
round trip.  Standing tasks become O(endpoints); responses fan back out
as short-lived per-group tasks only when they arrive.

The per-tick send *plan* stays host-event-driven (log appends, acks and
the engine's event masks trigger :meth:`Replicator.pump`); the plane is
the dispatch layer that turns those plans into endpoint-batched wire
traffic — the generalization of HeartbeatHub from beats to votes and
entry-bearing AppendEntries.

Ordering contract: ONE drain RPC in flight per endpoint (stop-and-wait
per endpoint pair, windowed WITHIN the batch), and a group submits at
most one append batch at a time — so a group's frames can never race
each other across RPCs, and the receiver (NodeManager._handle_multi_
append) only needs in-batch per-group ordering.  Throughput per group
is window x batch per endpoint round trip, same as the former
per-(group, peer) inflight FIFO, but the round trip is shared by every
group on the endpoint pair.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

from tpuraft.rpc.messages import BatchRequest, ErrorResponse
from tpuraft.rpc.transport import RpcError, is_no_method

LOG = logging.getLogger(__name__)


def _consume(t: "asyncio.Task") -> None:
    if not t.cancelled():
        t.exception()


# graftcheck: loop-confined — the queue/lane state is only touched by
# submit_* calls and drain tasks on the owning process's event loop
class EndpointSender:
    """Batches every pending protocol send to one destination endpoint.

    Items:
      - votes: (node, RequestVoteRequest, async cb) — cb fires as its
        own short task per response; silence on error (same contract as
        a dropped direct RPC).
      - append batches: (replicator, [AppendEntriesRequest, ...]) — the
        whole batch resolves through replicator.on_batch_responses /
        on_batch_error, in send order.

    Two lanes: appends keep strict ONE-RPC-in-flight stop-and-wait (the
    per-group ordering contract); votes have NO ordering constraint, so
    they drain on their own lane with several chunked RPCs in flight —
    an election herd at high group counts must not queue behind the
    appends' round trips or behind its own serialization (a 16K-group
    herd's votes per endpoint pair otherwise drain slower than the
    vote-round timeout, and no round ever completes).
    """

    # cap per append RPC: bounds receiver fan-out burst (each item may
    # carry entries + a disk flush) and response-task burst
    MAX_ITEMS_PER_RPC = 128
    # votes are tiny (no entries, no disk): bigger chunks, more lanes
    MAX_VOTES_PER_RPC = 1024
    VOTE_LANES = 4

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._votes: list[tuple[object, object, Callable]] = []
        self._appends: list[tuple[object, list, float]] = []
        self._task: Optional[asyncio.Task] = None
        self._round_pending: list[tuple[object, list, float]] = []
        self._vote_tasks: set = set()
        self._transport = None
        self._legacy = False  # receiver lacks multi_* handlers
        self.rpcs_sent = 0
        self.items_sent = 0

    # -- submit --------------------------------------------------------------

    def submit_vote(self, node, req, cb) -> None:
        self._votes.append((node, req, cb))
        self._transport = node.transport
        self._kick_votes()

    def submit_append(self, replicator, reqs: list) -> None:
        node = replicator._node
        self._appends.append(
            (replicator, reqs, node.options.election_timeout_ms))
        self._transport = node.transport
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._drain())
            self._task.add_done_callback(_consume)

    def _kick_votes(self) -> None:
        while self._votes and len(self._vote_tasks) < self.VOTE_LANES:
            chunk = self._votes[:self.MAX_VOTES_PER_RPC]
            del self._votes[:self.MAX_VOTES_PER_RPC]
            items = [req for _n, req, _cb in chunk]
            routes = [("v", cb, node) for node, _req, cb in chunk]
            # groups with DIFFERENT election timeouts share the chunk:
            # budget for the slowest, or a short-timeout group submitted
            # last would expire every co-batched long-timeout group's
            # round early (and vice versa starve retries)
            timeout_ms = max(n.options.election_timeout_ms
                             for _k, _cb, n in routes)
            t = asyncio.ensure_future(
                self._send_chunk(items, routes, timeout_ms))
            self._vote_tasks.add(t)

            def _done(tt, self=self):
                self._vote_tasks.discard(tt)
                _consume(tt)
                self._kick_votes()  # drain what queued meanwhile

            t.add_done_callback(_done)

    def queued(self) -> int:
        return len(self._votes) + sum(len(r) for _, r, _t in self._appends)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for t in list(self._vote_tasks):
            t.cancel()
        self._vote_tasks.clear()
        self._fail_all()

    def _fail_all(self) -> None:
        votes, self._votes = self._votes, []
        appends, self._appends = self._appends, []
        # the in-flight round's unresolved batches too: stranding them
        # would leave their replicators _pending=True forever (pump
        # gated, replication silently stopped for the pair)
        pending, self._round_pending = self._round_pending, []
        for rep, *_ in pending + appends:
            self._spawn(rep.on_batch_error())
        del votes  # silence, like a dropped RPC

    @staticmethod
    def _spawn(coro) -> None:
        t = asyncio.ensure_future(coro)
        t.add_done_callback(_consume)

    # -- drain ---------------------------------------------------------------

    async def _drain(self) -> None:
        """Append lane: strictly sequential chunk RPCs (the per-group
        ordering contract)."""
        try:
            while self._appends:
                appends, self._appends = self._appends, []
                await self._round(appends)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a sender bug must not silence
            LOG.exception("endpoint sender %s crashed", self.endpoint)
            self._fail_all()

    async def _round(self, appends) -> None:
        # an append batch never straddles chunks (its responses resolve
        # as one unit), and chunks go out strictly sequentially so
        # per-group order holds regardless.  _round_pending tracks the
        # not-yet-resolved tail so a mid-round cancel/crash can fail
        # exactly the stranded batches (see _fail_all).
        self._round_pending = list(appends)
        chunk_items: list = []
        chunk_routes: list = []  # ("a", rep, count)
        chunk_timeout = 0.0

        async def flush_chunk():
            nonlocal chunk_timeout
            if not chunk_items:
                return
            items, routes = list(chunk_items), list(chunk_routes)
            timeout_ms, chunk_timeout = chunk_timeout, 0.0
            chunk_items.clear()
            chunk_routes.clear()
            await self._send_chunk(items, routes, timeout_ms)
            done = {id(r[1]) for r in routes}
            self._round_pending = [b for b in self._round_pending
                                   if id(b[0]) not in done]

        for rep, reqs, tmo in appends:
            if chunk_items and (
                    len(chunk_items) + len(reqs) > self.MAX_ITEMS_PER_RPC):
                await flush_chunk()
            chunk_items.extend(reqs)
            chunk_routes.append(("a", rep, len(reqs)))
            chunk_timeout = max(chunk_timeout, tmo)  # budget for slowest
        await flush_chunk()

    async def _send_chunk(self, items: list, routes: list,
                          timeout_ms: float) -> None:
        if self._legacy:
            await self._send_legacy(items, routes)
            return
        method = "multi_vote" if routes[0][0] == "v" else "multi_append"
        self.rpcs_sent += 1
        self.items_sent += len(items)
        try:
            resp = await self._transport.call(
                self.endpoint, method, BatchRequest(items=items),
                timeout_ms=timeout_ms)
            acks = resp.items
        except RpcError as e:
            if is_no_method(e):
                # receiver predates the batch plane: resend these as
                # single RPCs and stay legacy for this endpoint
                self._legacy = True
                await self._send_legacy(items, routes)
                return
            self._dispatch_error(routes)
            return
        except Exception:  # noqa: BLE001
            LOG.exception("batch RPC to %s failed", self.endpoint)
            self._dispatch_error(routes)
            return
        if len(acks) != len(items):
            self._dispatch_error(routes)
            return
        i = 0
        slow_votes = []
        for route in routes:
            if route[0] == "v":
                ack = acks[i]
                i += 1
                if not isinstance(ack, ErrorResponse):
                    # INLINE, not spawned, when the node's meta storage
                    # is volatile: a 16K-group election herd's response
                    # tasks otherwise pile up faster than the loop
                    # drains them (measured: 35K stacked tasks, tick
                    # rate collapsed 5x, zero groups converging).
                    # Inline consumption is the backpressure — the next
                    # vote chunk only ships once this chunk's responses
                    # are processed.  With DURABLE meta a winning round
                    # fsyncs {term, votedFor} inside the handler, which
                    # must not head-of-line-block up to 1023 sibling
                    # responses — those gather below instead.
                    node = route[2]
                    if getattr(node._meta, "SYNC_CHEAP", False):
                        try:
                            await route[1](ack)
                        except Exception:  # noqa: BLE001 — one group's
                            LOG.exception("vote response handler failed")
                    else:
                        slow_votes.append(route[1](ack))
            else:
                _k, rep, count = route
                self._spawn(rep.on_batch_responses(acks[i:i + count]))
                i += count
        if slow_votes:
            # ONE awaited gather instead of len(slow_votes) spawned
            # tasks: task count stays O(vote lanes), the handlers run
            # concurrently — so their meta fsyncs coalesce into shared
            # group-commit rounds (multimeta://) — and awaiting inline
            # keeps the lane's backpressure: the next vote chunk ships
            # only after this chunk's {term, votedFor} persists land.
            for r in await asyncio.gather(*slow_votes,
                                          return_exceptions=True):
                if isinstance(r, BaseException) and not isinstance(
                        r, asyncio.CancelledError):
                    LOG.error("vote response handler failed: %r", r)

    def _dispatch_error(self, routes) -> None:
        for route in routes:
            if route[0] == "a":
                self._spawn(route[1].on_batch_error())
            # votes: silence, like a dropped direct RPC

    async def _send_legacy(self, items: list, routes: list) -> None:
        """Per-item RPCs for receivers without batch handlers."""
        i = 0
        for route in routes:
            if route[0] == "v":
                req, cb, node = items[i], route[1], route[2]
                i += 1

                async def one_vote(req=req, cb=cb, node=node):
                    try:
                        resp = await node.transport.request_vote(
                            self.endpoint, req,
                            timeout_ms=node.options.election_timeout_ms)
                    except RpcError:
                        return
                    await cb(resp)

                self._spawn(one_vote())
            else:
                _k, rep, count = route
                reqs = items[i:i + count]
                i += count
                self._spawn(self._legacy_appends(rep, reqs))

    async def _legacy_appends(self, rep, reqs: list) -> None:
        await sequential_appends(rep, self.endpoint, reqs)


async def sequential_appends(rep, endpoint: str, reqs: list,
                             timed: bool = False) -> None:
    """Per-frame append_entries fallback shared by legacy-endpoint mode
    and _DirectSender (bare managerless nodes): same resolution contract
    as a batch — acks in order, the tail failed on first error (the
    remaining frames would arrive out of order)."""
    node = rep._node
    acks: list = []
    for req in reqs:
        try:
            if timed:
                with node.metrics.timer("replicate-entries"):
                    acks.append(await node.transport.append_entries(
                        endpoint, req,
                        timeout_ms=node.options.election_timeout_ms))
            else:
                acks.append(await node.transport.append_entries(
                    endpoint, req,
                    timeout_ms=node.options.election_timeout_ms))
        except RpcError:
            acks.append(ErrorResponse(0, "send failed"))
            break
    while len(acks) < len(reqs):
        acks.append(ErrorResponse(0, "not sent"))
    await rep.on_batch_responses(acks)


# graftcheck: loop-confined
class SendPlane:
    """All endpoint senders of one process endpoint (lives on the
    NodeManager, like the HeartbeatHub)."""

    def __init__(self) -> None:
        self._senders: dict[str, EndpointSender] = {}

    def sender(self, endpoint: str) -> EndpointSender:
        s = self._senders.get(endpoint)
        if s is None:
            s = self._senders[endpoint] = EndpointSender(endpoint)
        return s

    def stats(self) -> dict:
        return {
            "endpoints": len(self._senders),
            "rpcs_sent": sum(s.rpcs_sent for s in self._senders.values()),
            "items_sent": sum(s.items_sent for s in self._senders.values()),
        }

    def shutdown(self) -> None:
        for s in self._senders.values():
            s.stop()
        self._senders.clear()
