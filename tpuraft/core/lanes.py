"""Worker lanes: dedicated pipeline-stage threads over shared-nothing queues.

*Scaling Replicated State Machines with Compartmentalization* (PAPERS.md)
decouples the roles one replica multiplexes so each scales on its own
core.  Inside one store process the asyncio loop already offloads RPC
framing and log fsync to the executor; the remaining single-core stages
are FSM apply and client-batch encode.  A :class:`WorkerLane` is the
smallest compartment that moves one such stage off the loop: ONE
dedicated thread draining ONE submission queue in FIFO order.

Design contract (what makes this safe without fine-grained locking):

- **shared-nothing ownership** — state a lane stage mutates (the raw KV
  store under FSM apply) is owned by the lane thread; every other access
  (read serving, snapshot serialization, split-point probing) must be
  SUBMITTED to the lane rather than touching the state from the loop;
- **FIFO ordering** — jobs run in submission order, so the raft apply
  order is preserved and a read submitted after the fence's applies see
  them (queue order is the happens-before edge);
- **loop-side completion** — results and exceptions hop back via
  ``call_soon_threadsafe``; the lane thread never touches asyncio
  futures directly.
"""

from __future__ import annotations

import asyncio
import queue
import threading
from typing import Optional


class WorkerLane:
    """One dedicated stage thread + its submission queue.

    Cross-thread state is confined to the internally-locked
    ``queue.SimpleQueue``; ``jobs`` is bumped only by the lane thread
    and read (monotonic, int-atomic under the GIL) by metrics.
    """

    def __init__(self, name: str = "lane"):
        self.name = name
        self.jobs = 0          # written by the lane thread only
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"tpuraft-{name}", daemon=True)
        self._thread.start()

    # -- loop side -----------------------------------------------------------

    def submit(self, fn, *args) -> asyncio.Future:
        """Queue ``fn(*args)`` onto the lane thread; await the returned
        future for its result (exceptions propagate).  Must be called
        from a running event loop."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._q.put((fn, args, loop, fut))
        return fut

    def depth(self) -> int:
        """Submitted-but-unfinished job count (approximate, for gauges)."""
        return self._q.qsize()

    async def aclose(self, timeout: float = 5.0) -> None:
        """Drain pending jobs, stop the thread; join off-loop."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        await asyncio.get_running_loop().run_in_executor(
            None, self._thread.join, timeout)

    def close_blocking(self, timeout: float = 5.0) -> None:
        """Non-async teardown (tests / atexit paths)."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout)

    # -- lane thread ---------------------------------------------------------

    @staticmethod
    def _resolve(fut: asyncio.Future, result, exc: Optional[BaseException]):
        if fut.done():        # loop torn down / caller gone mid-flight
            return
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args, loop, fut = item
            try:
                result, exc = fn(*args), None
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                result, exc = None, e
            self.jobs += 1
            try:
                loop.call_soon_threadsafe(self._resolve, fut, result, exc)
            except RuntimeError:
                return  # loop closed under us: shutting down
