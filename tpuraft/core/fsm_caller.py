"""FSMCaller: serialized pipeline into the user StateMachine.

Reference parity: ``core:core/FSMCallerImpl`` (SURVEY.md §3.1) — the
Disruptor + ApplyTaskHandler becomes a single asyncio consumer task; all
StateMachine callbacks (apply batches, snapshot save/load, role events)
run on it in submission order, so user code never sees concurrency.
"""

from __future__ import annotations

import asyncio
from collections import deque
import logging
import time
from typing import Awaitable, Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.entity import EntryType, LogEntry, LogId, PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.util.trace import TRACER as _TRACE

LOG = logging.getLogger(__name__)


class FSMCaller:
    def __init__(self, fsm: StateMachine, log_manager, apply_batch: int = 32,
                 on_error: Optional[Callable[[Status], Awaitable[None]]] = None,
                 health=None, trace_proc: str = "fsm", apply_lane=None):
        self._fsm = fsm
        self._lm = log_manager
        self._apply_batch = apply_batch
        self._node_on_error = on_error
        self._trace_proc = trace_proc
        # apply worker lane (compartmentalization): when set AND the FSM
        # exposes a sync ``apply_sync(it)``, DATA runs execute on the
        # lane thread — the loop only awaits the hop, so a saturated
        # store applies on a second core.  Closures the FSM fires on the
        # lane must be thread-safe (KVClosure hops back via
        # call_soon_threadsafe); the serialized-queue contract holds
        # because _drain awaits each lane hop before the next event.
        self._apply_lane = apply_lane
        self.lane_batches = 0   # apply batches that rode the lane
        # gray-failure signal: committed-minus-applied depth, reported
        # to the store's HealthTracker on every commit advance — a
        # saturated/slow FSM shows up as a growing backlog long before
        # client timeouts do
        self._health = health
        self.last_applied_index = 0
        self.last_applied_term = 0
        self._committed_index = 0
        # apply-plane observability (fleet metrics): batches through
        # on_apply and DATA entries they carried — the store engine
        # aggregates these across regions, so mean entries/batch (the
        # write plane's apply amortization) is scrapeable live
        self.apply_batches = 0
        self.applied_entries = 0
        self._closures: dict[int, Callable[[Status], None]] = {}
        # pipelined apply (Task.ack_at_commit): indices whose closure
        # fires at COMMIT, with the FSM apply running behind in
        # coalesced batches.  Staged in increasing index order (the
        # node stages entries monotonically under its lock), so firing
        # is a popleft scan, not a dict walk.
        self._eager: deque = deque()
        self.eager_acked = 0   # closures fired at commit (observability)
        # demand-spawned drain (r4): a standing task per FSMCaller was
        # O(nodes) standing tasks per process — at 16K groups x 3
        # replicas that alone is 48K idle tasks (the election-starvation
        # regime BENCH_SCALE r3 measured).  Events queue here and one
        # short-lived drain task runs only while events exist.
        self._queue: deque = deque()
        self._task: Optional[asyncio.Task] = None
        self._shut = False
        self._error: Optional[Status] = None
        self._applied_waiters: list[tuple[int, asyncio.Future]] = []
        # node hook: conf entry committed (drives membership-change stages)
        self.on_configuration_applied: Optional[
            Callable[[LogEntry], Awaitable[None]]] = None

    def replace_fsm(self, fsm: StateMachine) -> None:
        """Witness adoption (Node._adopt_witness_mode): swap the user
        FSM for the null witness FSM.  Runs on the node loop between
        queue drains; events already queued simply land on the new FSM
        — their payloads are stripped/irrelevant on a witness."""
        self._fsm = fsm

    async def init(self, bootstrap_id: LogId) -> None:
        self.last_applied_index = bootstrap_id.index
        self.last_applied_term = bootstrap_id.term
        self._committed_index = bootstrap_id.index

    async def shutdown(self) -> None:
        self._enqueue(("shutdown", None))
        if self._task is not None:
            await self._task
            self._task = None

    def _enqueue(self, item) -> None:
        if self._shut:
            return
        self._queue.append(item)
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._drain())

    # -- producers (called from node / ballot box) ---------------------------

    def append_pending_closure(self, index: int, done: Callable[[Status], None],
                               ack_at_commit: bool = False) -> None:
        self._closures[index] = done
        if ack_at_commit:
            self._eager.append(index)

    def fail_pending_closures(self, status: Status) -> None:
        """New leader emerged / stepping down: pending tasks won't commit here."""
        for done in self._closures.values():
            try:
                done(status)
            except Exception:
                LOG.exception("closure failed")
        self._closures.clear()
        self._eager.clear()

    def on_committed(self, index: int) -> None:
        if index <= self._committed_index:
            return
        self._committed_index = index
        if self._health is not None:
            self._health.note_apply_depth(index - self.last_applied_index)
        if self._eager and self._error is None:
            # ack-at-commit: blind writes resolve their proposers NOW —
            # commitment is their linearization point and their result
            # is known a priori — while the FSM applies behind in
            # coalesced batches.  A poisoned pipeline skips this (those
            # closures fail through fail_pending_closures instead).
            while self._eager and self._eager[0] <= index:
                done = self._closures.pop(self._eager.popleft(), None)
                if done is None:
                    continue
                self.eager_acked += 1
                try:
                    done(Status.OK())
                except Exception:
                    LOG.exception("eager closure failed")
        self._enqueue(("committed", index))

    def on_leader_start(self, term: int) -> None:
        self._enqueue(("leader_start", term))

    def on_leader_stop(self, status: Status) -> None:
        self._enqueue(("leader_stop", status))

    def on_start_following(self, leader: PeerId, term: int) -> None:
        self._enqueue(("start_following", (leader, term)))

    def on_stop_following(self, leader: PeerId, term: int) -> None:
        self._enqueue(("stop_following", (leader, term)))

    def on_error(self, status: Status) -> None:
        self._enqueue(("error", status))

    def poison(self, status: Status) -> None:
        """Externally-detected fatal error (e.g. divergence below the
        applied index): poison the apply pipeline exactly like an
        internal `_set_error` — no further committed/snapshot events
        reach the FSM — and deliver `on_error` through the queue.  Sync
        so the node can call it while holding its lock."""
        if self._error is None:
            self._error = status
            self._enqueue(("error", status))

    async def on_snapshot_save(self, writer, done: Callable[[Status], None]) -> None:
        self._enqueue(("snapshot_save", (writer, done)))

    async def on_snapshot_load(self, reader) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._enqueue(("snapshot_load", (reader, fut)))
        return fut

    # -- applied-index waiters (ReadOnlyService) -----------------------------

    def wait_applied(self, index: int) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        if self.last_applied_index >= index:
            fut.set_result(self.last_applied_index)
        else:
            self._applied_waiters.append((index, fut))
        return fut

    def _wake_applied_waiters(self) -> None:
        rest = []
        for idx, fut in self._applied_waiters:
            if fut.done():
                continue
            if self.last_applied_index >= idx:
                fut.set_result(self.last_applied_index)
            else:
                rest.append((idx, fut))
        self._applied_waiters = rest

    # -- consumer ------------------------------------------------------------

    async def _drain(self) -> None:
        while self._queue:
            kind, arg = self._queue.popleft()
            try:
                if kind == "shutdown":
                    self._shut = True
                    await self._fsm.on_shutdown()
                    return
                if self._error is not None and kind not in ("error",):
                    continue  # poisoned: only error propagation continues
                if kind == "committed":
                    await self._do_committed(arg)
                elif kind == "leader_start":
                    await self._fsm.on_leader_start(arg)
                elif kind == "leader_stop":
                    await self._fsm.on_leader_stop(arg)
                elif kind == "start_following":
                    await self._fsm.on_start_following(*arg)
                elif kind == "stop_following":
                    await self._fsm.on_stop_following(*arg)
                elif kind == "snapshot_save":
                    writer, done = arg
                    await self._fsm.on_snapshot_save(writer, done)
                elif kind == "snapshot_save_custom":
                    # SnapshotExecutor wrapper: captures applied-id meta
                    # at the moment the save runs in this serialized queue
                    writer, done, wrapper = arg
                    await wrapper(writer, done)
                elif kind == "snapshot_load":
                    reader, fut = arg
                    try:
                        ok = await self._fsm.on_snapshot_load(reader)
                        if ok:
                            meta = reader.load_meta()
                            self.last_applied_index = meta.last_included_index
                            self.last_applied_term = meta.last_included_term
                            self._committed_index = max(
                                self._committed_index, meta.last_included_index)
                            self._wake_applied_waiters()
                        if not fut.done():
                            fut.set_result(ok)
                    except Exception as exc:
                        if not fut.done():
                            fut.set_exception(exc)
                elif kind == "error":
                    await self._fsm.on_error(arg)
            except Exception:
                LOG.exception("FSMCaller %s handler crashed", kind)
                await self._set_error(Status.error(
                    RaftError.ESTATEMACHINE, f"{kind} handler crashed"))

    async def _set_error(self, status: Status) -> None:
        if self._error is None:
            self._error = status
            try:
                await self._fsm.on_error(status)
            except Exception:
                LOG.exception("on_error crashed")
            if self._node_on_error:
                await self._node_on_error(status)

    async def _do_committed(self, committed_index: int) -> None:
        while self.last_applied_index < committed_index and self._error is None:
            first = self.last_applied_index + 1
            batch_entries: list[LogEntry] = []
            data_entries: list[LogEntry] = []
            closures: list[Optional[Callable[[Status], None]]] = []
            idx = first
            while idx <= committed_index and len(batch_entries) < self._apply_batch:
                e = self._lm.get_entry(idx)
                if e is None:
                    await self._set_error(Status.error(
                        RaftError.EINTERNAL, f"committed entry {idx} missing"))
                    return
                batch_entries.append(e)
                idx += 1
            # split: DATA entries go to user FSM; CONFIGURATION/NO_OP handled
            # by the framework, batch boundaries preserved in order
            pos = 0
            while pos < len(batch_entries):
                e = batch_entries[pos]
                if e.type == EntryType.DATA:
                    run_start = pos
                    while (pos < len(batch_entries)
                           and batch_entries[pos].type == EntryType.DATA):
                        pos += 1
                    run = batch_entries[run_start:pos]
                    run_closures = [self._closures.pop(x.id.index, None) for x in run]
                    it = Iterator(run, run_closures)
                    # trace plane: the apply stage of any traced entry
                    # in this run (one span per traced entry; the run
                    # applies as one batch, so they share the envelope)
                    tids = ([x.trace_id for x in run if x.trace_id]
                            if _TRACE.enabled else [])
                    a0 = time.perf_counter() if tids else 0.0
                    sync_apply = (getattr(self._fsm, "apply_sync", None)
                                  if self._apply_lane is not None else None)
                    try:
                        if sync_apply is not None:
                            # lane apply: the sync body runs on the
                            # store's apply thread; per-op closures hop
                            # back to this loop inside KVClosure, and
                            # post-apply loop-confined bookkeeping
                            # (heat) runs here via on_lane_applied
                            post = await self._apply_lane.submit(
                                sync_apply, it)
                            self.lane_batches += 1
                            post_fn = getattr(self._fsm, "on_lane_applied",
                                              None)
                            if post_fn is not None:
                                post_fn(post)
                        else:
                            await self._fsm.on_apply(it)
                    except Exception:
                        LOG.exception("StateMachine.on_apply crashed")
                        await self._set_error(Status.error(
                            RaftError.ESTATEMACHINE, "on_apply raised"))
                        return
                    self.apply_batches += 1
                    self.applied_entries += len(run)
                    if tids:
                        a1 = time.perf_counter()
                        for tid in tids:
                            _TRACE.span(tid, "fsm_apply", a0, a1,
                                        proc=self._trace_proc,
                                        entries=len(run))
                    if it.stopped_status is not None:
                        await self._set_error(it.stopped_status)
                        return
                    # auto-complete closures the user didn't run
                    for x, done in zip(run, run_closures):
                        if done is not None:
                            try:
                                done(Status.OK())
                            except Exception:
                                LOG.exception("task closure failed")
                    self.last_applied_index = run[-1].id.index
                    self.last_applied_term = run[-1].id.term
                else:
                    if e.type == EntryType.CONFIGURATION:
                        conf = Configuration(list(e.peers or []),
                                             list(e.learners or []),
                                             list(e.witnesses or []))
                        try:
                            await self._fsm.on_configuration_committed(conf)
                        except Exception:
                            LOG.exception("on_configuration_committed crashed")
                        if self.on_configuration_applied is not None:
                            await self.on_configuration_applied(e)
                    done = self._closures.pop(e.id.index, None)
                    if done is not None:
                        try:
                            done(Status.OK())
                        except Exception:
                            LOG.exception("conf closure failed")
                    self.last_applied_index = e.id.index
                    self.last_applied_term = e.id.term
                    pos += 1
            self._lm.set_applied_index(self.last_applied_index)
            self._wake_applied_waiters()
