"""User state machine contract.

Reference parity: ``core:StateMachine`` + ``core:core/StateMachineAdapter``
+ ``core:core/IteratorImpl`` (SURVEY.md §9): ``on_apply(iterator)`` is the
only required method; committed entries arrive in batches through the
iterator, each with its index/term and (on the leader) the Task's done
closure.
"""

from __future__ import annotations

import logging
from typing import Awaitable, Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.entity import LogEntry
from tpuraft.errors import Status

LOG = logging.getLogger(__name__)


class Iterator:
    """Batch iterator over committed DATA entries (reference: IteratorImpl).

    Usage in on_apply::

        while it.valid():
            process(it.data())
            it.next()

    ``done()`` is the leader-side completion closure (None on followers);
    the framework runs it with Status.OK() automatically after on_apply
    unless the user already ran it.
    """

    def __init__(self, entries: list[LogEntry],
                 closures: list[Optional[Callable[[Status], None]]]):
        self._entries = entries
        self._closures = closures
        self._pos = 0
        self.stopped_status: Optional[Status] = None

    def valid(self) -> bool:
        return self._pos < len(self._entries) and self.stopped_status is None

    def data(self) -> bytes:
        return self._entries[self._pos].data

    def index(self) -> int:
        return self._entries[self._pos].id.index

    def term(self) -> int:
        return self._entries[self._pos].id.term

    def done(self) -> Optional[Callable[[Status], None]]:
        return self._closures[self._pos]

    def next(self) -> None:
        self._pos += 1

    def set_error_and_rollback(self, ntail: int = 1, status: Optional[Status] = None
                               ) -> None:
        """Stop applying; the current batch from pos-ntail is not consumed
        (reference: Iterator#setErrorAndRollback)."""
        self._pos = max(0, self._pos - ntail)
        self.stopped_status = status or Status.error(10002, "state machine error")

    @property
    def applied_upto(self) -> int:
        """Last index actually consumed (pos-1's index)."""
        if self._pos == 0:
            return self._entries[0].id.index - 1 if self._entries else 0
        return self._entries[self._pos - 1].id.index


class StateMachine:
    """Override on_apply at minimum. All methods run on the node's loop,
    serialized — never call back into Node synchronously from them."""

    async def on_apply(self, it: Iterator) -> None:
        raise NotImplementedError

    async def on_shutdown(self) -> None:
        pass

    async def on_snapshot_save(self, writer, done: Callable[[Status], None]) -> None:
        """Write state into ``writer`` (SnapshotWriter), then done(OK)."""
        done(Status.error(1, "snapshot not supported"))

    async def on_snapshot_load(self, reader) -> bool:
        return False

    async def on_leader_start(self, term: int) -> None:
        pass

    async def on_leader_stop(self, status: Status) -> None:
        pass

    async def on_error(self, status: Status) -> None:
        LOG.error("raft error: %s", status)

    async def on_configuration_committed(self, conf: Configuration) -> None:
        pass

    async def on_start_following(self, leader_id, term: int) -> None:
        pass

    async def on_stop_following(self, leader_id, term: int) -> None:
        pass


# the reference ships an adapter with no-op defaults; ours IS the base class
StateMachineAdapter = StateMachine


# graftcheck: loop-confined — FSMCaller runs every callback serialized
# on the node's event loop
class WitnessStateMachine(StateMachine):
    """The null FSM a WITNESS node runs: a witness journals log
    METADATA only (its incoming appends are payload-stripped), so there
    is nothing to apply and nothing to snapshot — the applied index
    still advances through the FSMCaller (commit bookkeeping, log
    compaction), and snapshots commit empty so prefix truncation keeps
    the metadata journal bounded.  ``Node.init`` installs this
    automatically when ``NodeOptions.witness`` is set, shadowing
    whatever FSM the hosting engine wired (a KV store's FSM applying a
    stripped entry would corrupt state)."""

    async def on_apply(self, it: Iterator) -> None:
        while it.valid():      # consume: payloads were stripped upstream
            it.next()

    async def on_snapshot_save(self, writer, done: Callable[[Status], None]
                               ) -> None:
        done(Status.OK())      # empty snapshot: meta-only compaction point

    async def on_snapshot_load(self, reader) -> bool:
        return True            # nothing to load; meta advances the log
