"""ReadOnlyService: linearizable reads via ReadIndex / leader lease.

Reference parity: ``core:core/ReadOnlyServiceImpl`` + ``NodeImpl#
handleReadIndexRequest`` (SURVEY.md §3.1, §4.4): batch read requests;
leader confirms its leadership for the batch (SAFE: one heartbeat quorum
round; LEASE_BASED: check the clock lease), pins readIndex = commitIndex,
then resolves once the FSM has applied up to it.  Followers forward to
the leader and wait locally.

Amortization layers (docs/operations.md "Read serving runbook"):
- per group: concurrent readers of one group share one confirmation
  round (``_join_round`` — the reference's batching);
- per store: when a store-level confirm batcher is attached
  (``tpuraft.rheakv.store_engine.ReadConfirmBatcher``), the SAFE quorum
  confirmations of ALL led groups on the store coalesce into one
  beat-plane round — one ``multi_beat_fast`` RPC per destination
  endpoint carries every group's read fence, the same way the
  HeartbeatHub amortizes idle beats;
- lease reads (``ReadOnlyOption.LEASE_BASED``) skip the round entirely,
  and on a HIBERNATING leader are served off the store-level liveness
  lease without waking the group (quiescence composition).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from tpuraft.entity import PeerId
from tpuraft.errors import RaftError, Status
from tpuraft.options import ReadOnlyOption
from tpuraft.rpc.messages import ReadIndexRequest
from tpuraft.rpc.transport import RpcError

LOG = logging.getLogger(__name__)


class ReadOnlyService:
    def __init__(self, node):
        self._node = node
        self._pending: list[asyncio.Future] = []
        self._round_task: Optional[asyncio.Task] = None
        # follower side: forwarded readIndex requests batch the same way
        # (reference: ReadOnlyServiceImpl batches on every node — one
        # forward RPC serves every reader queued for that round)
        self._fwd_pending: list[asyncio.Future] = []
        self._fwd_task: Optional[asyncio.Task] = None
        # store-level SAFE-confirmation amortizer (attached by the
        # StoreEngine for region groups; None = per-group rounds)
        self._confirm_batcher = None
        # read-plane counters (surfaced via RaftRawKVStore/StoreEngine
        # describe + the bench/soak stats lines)
        self.reads_served = 0     # read_index() calls resolved
        self.lease_serves = 0     # confirmed by the leader lease alone
        self.safe_rounds = 0      # per-group SAFE heartbeat rounds run
        self.batched_confirms = 0  # SAFE confirms amortized store-wide
        self.fwd_rounds = 0       # forward RPCs sent (follower side)
        self.fwd_redirects = 0    # leader-hint re-probes after rejection
        # LEASE_BASED configured but the lease didn't hold (expired,
        # drift-bound shrank it, or the clock sentinel fenced it):
        # the read fell back to a SAFE quorum round — the soak's
        # clock-chaos oracle counts these (ISSUE 18)
        self.lease_fallbacks = 0

    def attach_confirm_batcher(self, batcher) -> None:
        """Route this group's SAFE quorum confirmations through a
        store-wide batcher (``ReadConfirmBatcher.confirm(node) ->
        bool``) so confirmations of many groups share beat-plane RPCs."""
        self._confirm_batcher = batcher

    def counters(self) -> dict:
        return {
            "reads_served": self.reads_served,
            "lease_serves": self.lease_serves,
            "safe_rounds": self.safe_rounds,
            "batched_confirms": self.batched_confirms,
            "fwd_rounds": self.fwd_rounds,
            "fwd_redirects": self.fwd_redirects,
            "lease_fallbacks": self.lease_fallbacks,
        }

    async def shutdown(self) -> None:
        for fut in self._pending + self._fwd_pending:
            if not fut.done():
                fut.set_exception(
                    _read_error(RaftError.ENODESHUTTING, "shutting down"))
        self._pending.clear()
        self._fwd_pending.clear()
        # cancel in-flight confirmation rounds: a round surviving
        # shutdown keeps issuing heartbeat/forward RPCs from a dead node
        for task in (self._round_task, self._fwd_task):
            if task is not None and not task.done():
                task.cancel()
        self._round_task = self._fwd_task = None

    async def read_index(self) -> int:
        """Public entry: returns an index I such that (a) I >= commit index
        at call time as observed by a confirmed leader, and (b) the local
        FSM has applied through I.  Reading local state after this is
        linearizable."""
        node = self._node
        if node.options.witness:
            # a witness is NEVER a read target: its FSM holds no state
            # (payload-stripped journal), so a "linearizable" local read
            # would return nothing at all.  Clients route reads to data
            # replicas; this guard catches whatever slips through.
            raise _read_error(
                RaftError.EPERM,
                "witness replica stores no state (not a read target)")
        if node.is_leader():
            idx = await self.leader_confirm_read_index()
        else:
            idx = await self._forward_to_leader()
        await node.fsm_caller.wait_applied(idx)
        self.reads_served += 1
        return idx

    async def leader_confirm_read_index(self) -> int:
        """Leader side: pin commitIndex, confirm leadership, return index.
        Batching: concurrent callers share one confirmation round."""
        return await self._join_round("_pending", "_round_task",
                                      self._leader_once)

    async def _join_round(self, pending_attr: str, task_attr: str,
                          once) -> int:
        """Enqueue one reader into the named batch and ensure a drain
        task is running; ``once()`` resolves a whole batch to an index
        (or raises for the whole batch)."""
        fut = asyncio.get_running_loop().create_future()
        getattr(self, pending_attr).append(fut)
        task = getattr(self, task_attr)
        if task is None or task.done():
            setattr(self, task_attr, asyncio.ensure_future(
                self._run_rounds(pending_attr, once)))
        return await fut

    async def _run_rounds(self, pending_attr: str, once) -> None:
        # Drain until no requests remain: futures appended WHILE a round
        # is resolving must be picked up by a follow-up round here —
        # callers only spawn a drain task when none is running, so
        # exiting with readers still pending would orphan them until the
        # next request happens to arrive (observed as client-timeout p99
        # tails).  This invariant serves BOTH the leader confirmation
        # rounds and the follower forward rounds.
        while getattr(self, pending_attr):
            batch = getattr(self, pending_attr)
            setattr(self, pending_attr, [])
            try:
                read_index = await once()
            except asyncio.CancelledError:
                # shutdown cancelled the round mid-flight: the batch was
                # already popped from pending, so shutdown()'s sweep
                # can't reach it — fail it here or its readers hang
                for fut in batch:
                    if not fut.done():
                        fut.set_exception(_read_error(
                            RaftError.ENODESHUTTING, "shutting down"))
                raise
            except ReadIndexError as e:
                for fut in batch:
                    if not fut.done():
                        fut.set_exception(_read_error(
                            e.status.raft_error, e.status.error_msg))
                continue
            except Exception as e:  # noqa: BLE001 — transport/storage error
                for fut in batch:
                    if not fut.done():
                        fut.set_exception(_read_error(
                            RaftError.EINTERNAL, f"readIndex round: {e!r}"))
                continue
            for fut in batch:
                if not fut.done():
                    fut.set_result(read_index)

    def _effective_eto_ms(self) -> int:
        """The ADOPTED election timeout: the engine's density floor may
        have raised the node's timeout after construction (EngineControl.
        _adopt_eto), and every read-side budget must track the adopted
        value — a budget derived from a stale shorter timeout times out
        forwarded reads on dense stores during the post-election no-op
        window."""
        ctrl_eto = getattr(self._node._ctrl, "_eto_ms", 0)
        return max(int(ctrl_eto), self._node.options.election_timeout_ms)

    async def _leader_once(self) -> int:
        # a fresh leader briefly cannot serve reads (safety gate below);
        # WAIT for the term's no-op to apply — normally single-digit ms
        # — instead of bouncing every post-election read with an error.
        # Budget: HALF the election timeout, so follower-FORWARDED reads
        # (whose RPC timeout is one election timeout) still get the
        # answer instead of timing out just as the leader resolves.
        node = self._node
        if node.ballot_box.last_committed_index < node._term_first_index:
            try:
                await asyncio.wait_for(
                    node.fsm_caller.wait_applied(node._term_first_index),
                    self._effective_eto_ms() / 2000.0)
            except asyncio.TimeoutError:
                pass   # fall through: _confirm_once fails closed
        ok, read_index = await self._confirm_once()
        if not ok:
            raise _read_error(RaftError.ERAFTTIMEDOUT,
                              "readIndex quorum confirmation failed")
        return read_index

    async def _confirm_once(self) -> tuple[bool, int]:
        node = self._node
        read_index = node.ballot_box.last_committed_index
        # SAFETY GATE: until this leader commits the first entry of its
        # OWN term (the election no-op), its lastCommittedIndex is a
        # follower-time carry-over that may LAG entries the previous
        # leader committed and acked — serving reads against it returns
        # state with acked writes missing (caught by the linearizability
        # soak as a stale read after a leader kill).  Reference:
        # ReadOnlyServiceImpl rejects reads until the current term has
        # a committed entry.
        if read_index < node._term_first_index:
            return False, read_index
        opt = node.options.raft_options.read_only_option
        if opt == ReadOnlyOption.LEASE_BASED:
            if node.leader_lease_is_valid():
                # served off the lease alone — NO quorum round, and no
                # wake: a HIBERNATING leader's lease rides the
                # store-level liveness lease (EngineControl.lease_valid
                # consults store_lease_quorum_ok while quiescent), so a
                # pure-read load leaves quiescent groups hibernated
                self.lease_serves += 1
                return True, read_index
            self.lease_fallbacks += 1
        # SAFE quorum round (or the lease lapsed): the round beats the
        # followers directly, and a beaten follower WAKES — the leader
        # must wake with it or its hibernation outlives its followers'
        # patience and they elect over it.  The wake sits HERE, after
        # the lease check, so lease-served reads never un-hibernate the
        # group (pre-fix: every SAFE-mode read woke it at the top).
        node._ctrl.note_activity()
        voters = len(node.conf_entry.conf.peers)
        if voters <= 1:
            return node.is_leader(), read_index
        if self._confirm_batcher is not None:
            # store-wide amortization: this group's fence rides one
            # beat-plane round shared with every other led group's
            self.batched_confirms += 1
            ok = await self._confirm_batcher.confirm(node)
            return ok and node.is_leader(), read_index
        self.safe_rounds += 1
        acks = 1 + await node.replicators.heartbeat_round()
        return acks >= voters // 2 + 1 and node.is_leader(), read_index

    async def _forward_to_leader(self) -> int:
        """Batched: concurrent forwarded readers share one RPC round.
        Sharing is linearizable — the shared index was obtained by an
        RPC SENT after every sharer's invoke (readers arriving while a
        round is in flight wait for the NEXT round)."""
        return await self._join_round("_fwd_pending", "_fwd_task",
                                      self._forward_once)

    async def _forward_once(self) -> int:
        """One forward round: probe the believed leader; on a rejection
        follow the responder's leader hint (trailing ReadIndexResponse
        field) within the same round — bounded chain, each hop tried
        once.  Exhaustion raises a RETRYABLE status (EAGAIN), never a
        terminal EPERM: 'not the leader' resolves within ~an election
        timeout, and the KV layer's retry engine probes the next
        candidate store exactly like _store_candidates' coverage
        contract promises."""
        node = self._node
        target = node.leader_id
        if target.is_empty():
            raise _read_error(RaftError.EAGAIN, "no known leader")
        tried: set[str] = set()
        last = "no known leader"
        while target is not None and not target.is_empty() \
                and str(target) not in tried and len(tried) < 3:
            tried.add(str(target))
            req = ReadIndexRequest(
                group_id=node.group_id,
                server_id=str(node.server_id),
                peer_id=str(target),
            )
            self.fwd_rounds += 1
            try:
                resp = await node.transport.read_index(
                    target.endpoint, req,
                    timeout_ms=self._effective_eto_ms())
            except RpcError as e:
                raise _read_error(
                    RaftError.ETIMEDOUT,
                    f"readIndex forward to {target} failed") from e
            if resp.success:
                return resp.index
            hint = getattr(resp, "leader_hint", "")
            last = (f"{target} rejected readIndex"
                    + (f"; hinted {hint}" if hint else ""))
            target = None
            if hint:
                try:
                    hinted = PeerId.parse(hint)
                except Exception:  # noqa: BLE001 — malformed hint
                    hinted = None
                if hinted is not None and hinted != node.server_id:
                    self.fwd_redirects += 1
                    target = hinted
        raise _read_error(RaftError.EAGAIN, f"readIndex forward: {last}")


class ReadIndexError(Exception):
    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status


def _read_error(code, msg) -> ReadIndexError:
    return ReadIndexError(Status.error(code, msg))
