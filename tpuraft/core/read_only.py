"""ReadOnlyService: linearizable reads via ReadIndex / leader lease.

Reference parity: ``core:core/ReadOnlyServiceImpl`` + ``NodeImpl#
handleReadIndexRequest`` (SURVEY.md §3.1, §4.4): batch read requests;
leader confirms its leadership for the batch (SAFE: one heartbeat quorum
round; LEASE_BASED: check the clock lease), pins readIndex = commitIndex,
then resolves once the FSM has applied up to it.  Followers forward to
the leader and wait locally.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from tpuraft.errors import RaftError, Status
from tpuraft.options import ReadOnlyOption
from tpuraft.rpc.messages import ReadIndexRequest
from tpuraft.rpc.transport import RpcError

LOG = logging.getLogger(__name__)


class ReadOnlyService:
    def __init__(self, node):
        self._node = node
        self._pending: list[asyncio.Future] = []
        self._round_task: Optional[asyncio.Task] = None

    async def shutdown(self) -> None:
        for fut in self._pending:
            if not fut.done():
                fut.set_exception(
                    _read_error(RaftError.ENODESHUTTING, "shutting down"))
        self._pending.clear()

    async def read_index(self) -> int:
        """Public entry: returns an index I such that (a) I >= commit index
        at call time as observed by a confirmed leader, and (b) the local
        FSM has applied through I.  Reading local state after this is
        linearizable."""
        node = self._node
        if node.is_leader():
            idx = await self.leader_confirm_read_index()
        else:
            idx = await self._forward_to_leader()
        await node.fsm_caller.wait_applied(idx)
        return idx

    async def leader_confirm_read_index(self) -> int:
        """Leader side: pin commitIndex, confirm leadership, return index.
        Batching: concurrent callers share one confirmation round."""
        node = self._node
        fut = asyncio.get_running_loop().create_future()
        self._pending.append(fut)
        if self._round_task is None or self._round_task.done():
            self._round_task = asyncio.ensure_future(self._run_round())
        return await fut

    async def _run_round(self) -> None:
        # Drain until no requests remain: futures appended WHILE a round is
        # confirming must be picked up by a follow-up round here — callers
        # only spawn a round task when none is running, so exiting with
        # _pending non-empty would orphan those readers until the next
        # request happens to arrive (observed as client-timeout p99 tails).
        while self._pending:
            batch, self._pending = self._pending, []
            try:
                ok, read_index = await self._confirm_once()
            except Exception as e:  # noqa: BLE001 — transport/storage error
                for fut in batch:
                    if not fut.done():
                        fut.set_exception(_read_error(
                            RaftError.EINTERNAL, f"readIndex round: {e!r}"))
                continue
            for fut in batch:
                if fut.done():
                    continue
                if ok:
                    fut.set_result(read_index)
                else:
                    fut.set_exception(_read_error(
                        RaftError.ERAFTTIMEDOUT,
                        "readIndex quorum confirmation failed"))

    async def _confirm_once(self) -> tuple[bool, int]:
        node = self._node
        read_index = node.ballot_box.last_committed_index
        # A commit index carried over from a prior term is still a valid
        # read barrier — those entries were committed by prior leaders
        # (reference: ReadOnlyServiceImpl's electing-state handling).
        opt = node.options.raft_options.read_only_option
        if opt == ReadOnlyOption.LEASE_BASED and node.leader_lease_is_valid():
            return True, read_index
        # SAFE: quorum heartbeat round
        voters = len(node.conf_entry.conf.peers)
        if voters <= 1:
            return node.is_leader(), read_index
        acks = 1 + await node.replicators.heartbeat_round()
        return acks >= voters // 2 + 1 and node.is_leader(), read_index

    async def _forward_to_leader(self) -> int:
        node = self._node
        leader = node.leader_id
        if leader.is_empty():
            raise _read_error(RaftError.EPERM, "no known leader")
        req = ReadIndexRequest(
            group_id=node.group_id,
            server_id=str(node.server_id),
            peer_id=str(leader),
        )
        try:
            resp = await node.transport.read_index(
                leader.endpoint, req,
                timeout_ms=node.options.election_timeout_ms)
        except RpcError as e:
            raise _read_error(RaftError.ETIMEDOUT,
                              f"readIndex forward to {leader} failed") from e
        if not resp.success:
            raise _read_error(RaftError.EPERM, "leader rejected readIndex")
        return resp.index


class ReadIndexError(Exception):
    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status


def _read_error(code, msg) -> ReadIndexError:
    return ReadIndexError(Status.error(code, msg))
