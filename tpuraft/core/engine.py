"""MultiRaftEngine: one device tick advances ALL raft groups in a process.

The north-star component (BASELINE.json): the per-group ``BallotBox``
quorum counting becomes rows of a ``[G, P]`` tensor; one jitted
``raft_tick`` per engine tick computes every group's commit advancement
on device.  Host Nodes keep the protocol envelope; their ballot boxes are
swapped for :class:`TpuBallotBox` via the ``ballot_box_factory`` seam
(the analog of plugging TpuBallotBox through the reference's
``JRaftServiceLoader`` SPI, leaving NodeImpl/FSMCaller/LogStorage
untouched).

Index-domain note: the device works in int32 *relative* indexes
(``abs - base[g]``); the engine re-bases a group whenever its relative
window approaches 2^28, so unbounded absolute indexes never overflow.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

import numpy as np

from tpuraft.conf import Configuration
from tpuraft.entity import PeerId
from tpuraft.options import TickOptions
from tpuraft.ops.tick import GroupState, TickParams

LOG = logging.getLogger(__name__)

_REBASE_LIMIT = 1 << 28


class TpuBallotBox:
    """Drop-in for core.ballot_box.BallotBox backed by the engine tensors.

    Mutations write numpy mirrors and mark the engine dirty; quorum math
    happens on device at the next engine tick.
    """

    def __init__(self, engine: "MultiRaftEngine", slot: int,
                 on_committed: Callable[[int], None]):
        self._engine = engine
        self.slot = slot
        self._on_committed = on_committed
        self.last_committed_index = 0
        self.pending_index = 0

    # -- leader side ---------------------------------------------------------

    def reset_pending_index(self, new_pending_index: int) -> None:
        e = self._engine
        self.pending_index = new_pending_index
        e.base[self.slot] = new_pending_index - 1
        e.pending_rel[self.slot] = 1
        e.match_abs[self.slot, :] = 0
        # commit baseline for the gate `q > commit_now`: nothing of THIS
        # leadership is committed yet (slot may be reused from a prior node)
        e.commit_abs[self.slot] = new_pending_index - 1
        e.leader_mask[self.slot] = True
        e.mark_dirty()

    def clear_pending(self) -> None:
        self.pending_index = 0
        e = self._engine
        e.leader_mask[self.slot] = False
        e.match_abs[self.slot, :] = 0

    def commit_at(self, peer: PeerId, match_index: int, conf: Configuration,
                  old_conf: Configuration) -> bool:
        """Record the ack; actual quorum reduce happens on device."""
        if self.pending_index == 0:
            return False
        e = self._engine
        col = e.peer_col(self.slot, peer)
        if col is None:
            return False
        if match_index > e.match_abs[self.slot, col]:
            e.match_abs[self.slot, col] = match_index
            e.mark_dirty()
        return False  # advancement is reported asynchronously by the tick

    def update_conf(self, conf: Configuration, old_conf: Configuration) -> None:
        self._engine.set_conf(self.slot, conf, old_conf)

    def close(self) -> None:
        self._engine.release(self)

    # -- follower side -------------------------------------------------------

    def set_last_committed_index(self, index: int) -> bool:
        if self.pending_index != 0:
            return False
        if index <= self.last_committed_index:
            return False
        self.last_committed_index = index
        self._on_committed(index)
        return True

    # engine callback
    def _advance(self, new_commit: int) -> None:
        if self.pending_index == 0:
            return
        if new_commit > self.last_committed_index:
            self.last_committed_index = new_commit
            self._on_committed(new_commit)


class MultiRaftEngine:
    """Per-process batched commit plane.  Start once, register each node's
    ballot box through :meth:`ballot_box_factory`."""

    def __init__(self, opts: Optional[TickOptions] = None):
        self.opts = opts or TickOptions()
        g, p = self.opts.max_groups, self.opts.max_peers
        self.G, self.P = g, p
        # numpy mirrors (host-owned truth between ticks)
        self.match_abs = np.zeros((g, p), np.int64)
        self.base = np.zeros(g, np.int64)
        self.pending_rel = np.ones(g, np.int32)
        self.voter_mask = np.zeros((g, p), bool)
        self.old_voter_mask = np.zeros((g, p), bool)
        self.leader_mask = np.zeros(g, bool)
        self.commit_abs = np.zeros(g, np.int64)
        self._peer_cols: list[dict[PeerId, int]] = [dict() for _ in range(g)]
        self._boxes: list[Optional[TpuBallotBox]] = [None] * g
        self._free = list(range(g - 1, -1, -1))
        self._dirty = False
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._tick_fn = None  # jitted quorum reduce (None => numpy path)
        self.ticks = 0
        self.commit_advances = 0

    # -- registry ------------------------------------------------------------

    def ballot_box_factory(self):
        """Returns a factory usable as Node(ballot_box_factory=...)."""

        def make(on_committed: Callable[[int], None]) -> TpuBallotBox:
            slot = self.alloc_slot()
            box = TpuBallotBox(self, slot, on_committed)
            self._boxes[slot] = box
            return box

        return make

    def alloc_slot(self) -> int:
        if not self._free:
            self._grow()
        return self._free.pop()

    def _grow(self) -> None:
        """Double group capacity in place.  Region splits mint new raft
        groups at runtime; a full engine must absorb them, not crash
        the new RegionEngine.  The next tick recompiles once for the
        new shape (jit caches per shape); doubling preserves
        divisibility by mesh_devices for the sharded path."""
        old_g = self.G
        new_g = old_g * 2

        def pad(a: np.ndarray, fill=0) -> np.ndarray:
            extra = np.full((old_g,) + a.shape[1:], fill, a.dtype)
            return np.concatenate([a, extra])

        self.match_abs = pad(self.match_abs)
        self.base = pad(self.base)
        self.pending_rel = pad(self.pending_rel, 1)
        self.voter_mask = pad(self.voter_mask)
        self.old_voter_mask = pad(self.old_voter_mask)
        self.leader_mask = pad(self.leader_mask)
        self.commit_abs = pad(self.commit_abs)
        self._peer_cols.extend(dict() for _ in range(old_g))
        self._boxes.extend([None] * old_g)
        self._free = list(range(new_g - 1, old_g - 1, -1))
        self.G = new_g
        LOG.info("engine grew: %d -> %d group slots", old_g, new_g)

    def release(self, box: TpuBallotBox) -> None:
        s = box.slot
        self._boxes[s] = None
        self.voter_mask[s] = False
        self.old_voter_mask[s] = False
        self.leader_mask[s] = False
        self.match_abs[s] = 0
        self.commit_abs[s] = 0
        self.base[s] = 0
        self.pending_rel[s] = 1
        self._peer_cols[s].clear()
        self._free.append(s)

    def set_conf(self, slot: int, conf: Configuration,
                 old_conf: Configuration) -> None:
        """Map peers to columns and set voter masks for a group."""
        cols = self._peer_cols[slot]
        all_peers = list(dict.fromkeys(
            conf.peers + old_conf.peers + conf.learners + old_conf.learners))
        # retain existing column assignments; add new peers to free columns
        used = set(cols.values())
        for peer in all_peers:
            if peer not in cols:
                col = next((i for i in range(self.P) if i not in used), None)
                if col is None:
                    raise RuntimeError(
                        f"group slot {slot}: {len(all_peers)} distinct peers "
                        f"exceed max_peers={self.P} engine columns")
                cols[peer] = col
                used.add(col)
        # drop stale peers
        for peer in [p for p in cols if p not in all_peers]:
            self.match_abs[slot, cols[peer]] = 0
            del cols[peer]
        vm = np.zeros(self.P, bool)
        ovm = np.zeros(self.P, bool)
        for peer in conf.peers:
            vm[cols[peer]] = True
        for peer in old_conf.peers:
            ovm[cols[peer]] = True
        self.voter_mask[slot] = vm
        self.old_voter_mask[slot] = ovm
        self.mark_dirty()

    def peer_col(self, slot: int, peer: PeerId) -> Optional[int]:
        return self._peer_cols[slot].get(peer)

    def mark_dirty(self) -> None:
        self._dirty = True

    def describe(self) -> str:
        """Live engine state for operators (the device-plane counterpart
        of Node#describe)."""
        used = sum(1 for b in self._boxes if b is not None)
        return (f"MultiRaftEngine<G={self.G} P={self.P} used={used} "
                f"backend={self.opts.backend} "
                f"mesh={self.opts.mesh_devices or 1} "
                f"ticks={self.ticks} commit_advances={self.commit_advances} "
                f"leaders={int(self.leader_mask.sum())}>")

    # -- tick loop -----------------------------------------------------------

    async def start(self) -> None:
        if self.opts.backend != "numpy":
            import jax

            from tpuraft.ops.ballot import joint_quorum_match_index

            if self.opts.mesh_devices and self.opts.mesh_devices > 1:
                # SPMD over the group axis: each chip reduces its own
                # group rows; upload scatters, download gathers (the
                # "vote-matrix over ICI" configuration in BASELINE.md)
                from tpuraft.parallel.mesh import group_shardings, make_mesh

                n = self.opts.mesh_devices
                if self.G % n != 0:
                    raise ValueError(
                        f"max_groups={self.G} not divisible by "
                        f"mesh_devices={n}")
                mesh = make_mesh(n)  # raises if fewer devices exist
                out, row = group_shardings(mesh)
                self._tick_fn = jax.jit(
                    joint_quorum_match_index,
                    in_shardings=(row, row, row),
                    out_shardings=out)
            else:
                # jitted once: eager per-tick dispatch would cost ~100ms
                # over a tunneled device and starve the asyncio loop
                self._tick_fn = jax.jit(joint_quorum_match_index)
        if self.opts.profile_dir:
            if self.opts.backend == "numpy":
                LOG.warning("profile_dir set but backend is numpy: the "
                            "XLA profiler only traces the jax tick path")
            else:
                import jax

                try:
                    # process-global: a second engine in the same
                    # process cannot start another trace — it keeps
                    # running without one instead of failing startup
                    jax.profiler.start_trace(self.opts.profile_dir)
                    self._profiling = True
                except Exception as e:  # noqa: BLE001
                    LOG.warning("profiler trace not started (another "
                                "engine's trace active?): %s", e)
        from tpuraft.util import describer

        describer.register(self)
        self._task = asyncio.ensure_future(self._loop())

    async def shutdown(self) -> None:
        self._stopped = True
        from tpuraft.util import describer

        describer.unregister(self)
        if getattr(self, "_profiling", False):
            import jax

            self._profiling = False
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — trace already stopped
                LOG.warning("profiler stop: %s", e)
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        interval = self.opts.tick_interval_ms / 1000.0
        while not self._stopped:
            await asyncio.sleep(interval)
            if self._dirty:
                self._dirty = False
                try:
                    self.tick_once()
                except Exception:
                    LOG.exception("engine tick failed")
                    self._dirty = True  # re-process pending acks next tick

    # -- the tick ------------------------------------------------------------

    def _rebase(self) -> None:
        hot = (self.match_abs.max(axis=1) - self.base) > _REBASE_LIMIT
        if hot.any():
            for s in np.nonzero(hot)[0]:
                new_base = self.commit_abs[s]
                self.pending_rel[s] = max(
                    1, self.pending_rel[s] - (new_base - self.base[s]))
                self.base[s] = new_base

    def tick_once(self) -> int:
        """One batched commit computation for all leader groups.  Returns
        number of groups whose commit advanced."""
        import jax.numpy as jnp

        self._rebase()
        rel = np.clip(self.match_abs - self.base[:, None], 0, None
                      ).astype(np.int32)
        commit_rel_now = np.clip(self.commit_abs - self.base, 0, None
                                 ).astype(np.int32)

        if self._tick_fn is not None:
            import jax

            with jax.profiler.TraceAnnotation("tpuraft.raft_tick"):
                q = np.asarray(self._tick_fn(
                    jnp.asarray(rel), jnp.asarray(self.voter_mask),
                    jnp.asarray(self.old_voter_mask)))
        else:  # numpy fallback (tiny deployments / no jax)
            q = _np_joint_quorum(rel, self.voter_mask, self.old_voter_mask)

        can = (self.leader_mask & (q >= self.pending_rel)
               & (q > commit_rel_now))
        advanced = 0
        self.ticks += 1
        for s in np.nonzero(can)[0]:
            box = self._boxes[s]
            if box is None:
                continue
            new_commit = int(self.base[s] + q[s])
            self.commit_abs[s] = new_commit
            advanced += 1
            box._advance(new_commit)
        self.commit_advances += advanced
        return advanced


def _np_joint_quorum(rel: np.ndarray, vm: np.ndarray, ovm: np.ndarray
                     ) -> np.ndarray:
    NEG = np.int32(-(2 ** 30))

    def order_stat(mask):
        v = np.where(mask, rel, NEG)
        sd = -np.sort(-v, axis=1)
        n = mask.sum(axis=1)
        qi = np.clip(n // 2, 0, rel.shape[1] - 1)
        picked = np.take_along_axis(sd, qi[:, None], axis=1)[:, 0]
        return np.where(n > 0, picked, NEG)

    new_q = order_stat(vm)
    old_q = order_stat(ovm)
    return np.where(ovm.any(axis=1), np.minimum(new_q, old_q), new_q)
