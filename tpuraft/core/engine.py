"""MultiRaftEngine: one device tick advances ALL raft groups in a process.

The north-star component (BASELINE.json): the per-group consensus
bookkeeping becomes rows of ``[G, P]`` tensors and ONE jitted
``raft_tick`` (tpuraft.ops.tick) per engine tick computes every group's
commit advancement, election-timeout firing, vote quorums, leader-lease
validity / dead-quorum step-down, and heartbeat scheduling on device —
the full SURVEY §8.1 device plane, not just the commit reduce.

Wiring: host Nodes get their ballot boxes from :meth:`ballot_box_factory`
(the analog of plugging TpuBallotBox through the reference's
``JRaftServiceLoader`` SPI).  With ``TickOptions.drive_protocol`` (the
default), the box also hands the node an :class:`EngineControl` — the
device-plane replacement for the reference's per-group RepeatedTimers
(``electionTimer``/``voteTimer``/``stepDownTimer``), the ``_peer_acks``
map behind ``NodeImpl#checkDeadNodes``, and the per-round vote tally of
``NodeImpl#handleRequestVoteResponse``.  The engine's numpy mirrors are
then the single source of truth for deadlines / acks / votes; the tick's
output masks schedule the slow-path protocol handlers, which re-verify
under the node lock (the host stays the single writer of protocol state,
mirroring NodeImpl's writeLock discipline).

Division of labor per event:
  election_due  -> Node._on_election_due (pre-vote / vote-timeout retry)
  elected       -> Node._on_engine_elected (becomeLeader)
  step_down     -> Node._on_engine_quorum_dead (checkDeadNodes)
  hb_due        -> batched empty-AppendEntries via HeartbeatHub.pulse
  commit        -> TpuBallotBox._advance -> FSMCaller.on_committed

The tick loop is ADAPTIVE: a dirty mark (new ack / vote / deadline
change) fires a tick immediately — commit acks are not quantized to a
fixed cadence — while consecutive ticks self-pace by the previous tick's
cost (slow tunneled devices batch more per dispatch).  Idle engines
sleep until the next election/heartbeat deadline, capped at
``tick_interval_ms``.

Index-domain note: the device works in int32 *relative* indexes
(``abs - base[g]``); the engine re-bases a group whenever its relative
window approaches 2^28, so unbounded absolute indexes never overflow.
Times are int32 ms since engine start, epoch-shifted before they near
2^30 (multi-week uptimes never overflow).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Callable, Optional

import numpy as np

from tpuraft.conf import Configuration
from tpuraft.entity import PeerId
from tpuraft.options import TickOptions
from tpuraft.util import clock as clockmod
from tpuraft.util.trace import RECORDER as _RECORDER
from tpuraft.ops.tick import (
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_INACTIVE,
    ROLE_LEADER,
)

LOG = logging.getLogger(__name__)

_REBASE_LIMIT = 1 << 28
_TIME_REBASE_MS = 1 << 30        # epoch-shift threshold (int32 headroom)
_NEG_I32 = -(2 ** 30)            # matches tpuraft.ops.ballot.NEG_INF_I32
# protocol-param defaults for slots no node has registered yet
_DEF_ETO_MS, _DEF_HB_MS, _DEF_LEASE_MS = 1000, 100, 900


class TpuBallotBox:
    """Drop-in for core.ballot_box.BallotBox backed by the engine tensors.

    Mutations write numpy mirrors and mark the engine dirty; quorum math
    happens on device at the next engine tick.
    """

    def __init__(self, engine: "MultiRaftEngine", slot: int,
                 on_committed: Callable[[int], None]):
        self._engine = engine
        self.slot = slot
        self._on_committed = on_committed
        self.last_committed_index = 0
        self.pending_index = 0

    # -- control-plane seam --------------------------------------------------

    def make_control(self, node) -> Optional["EngineControl"]:
        """Hand the node the engine's device control plane (or None to
        keep host timers, when drive_protocol is off)."""
        if not self._engine.opts.drive_protocol:
            return None
        return EngineControl(self._engine, node, self)

    # -- leader side ---------------------------------------------------------

    def reset_pending_index(self, new_pending_index: int) -> None:
        e = self._engine
        self.pending_index = new_pending_index
        e.base[self.slot] = new_pending_index - 1
        e.pending_rel[self.slot] = 1
        e.match_abs[self.slot, :] = 0
        # commit baseline for the device gate `q > commit_now`: nothing of
        # THIS leadership is committed yet (slot may be reused from a
        # prior node)
        e.commit_abs[self.slot] = new_pending_index - 1
        e.role[self.slot] = ROLE_LEADER
        e.mark_dirty()

    def clear_pending(self) -> None:
        self.pending_index = 0
        e = self._engine
        # a controlled slot stays an engine-scheduled follower; a bare
        # box (commit plane only) goes inactive
        e.role[self.slot] = (
            ROLE_FOLLOWER if e.has_ctrl[self.slot] else ROLE_INACTIVE)
        e.match_abs[self.slot, :] = 0

    def commit_at(self, peer: PeerId, match_index: int, conf: Configuration,
                  old_conf: Configuration) -> bool:
        """Record the ack.  With ``TickOptions.eager_commit`` (default)
        the ack that completes a quorum advances the commit point RIGHT
        HERE — one scalar order statistic over this slot's [P] row, the
        same joint math the device tick reduces — instead of waiting
        out the tick pace.  The tick remains the batch plane (and the
        safety net: it recomputes the same value); a hot group's
        quorum closes on the ack path, event-driven, exactly like the
        scalar BallotBox."""
        if self.pending_index == 0:
            return False
        e = self._engine
        col = e.peer_col(self.slot, peer)
        if col is None:
            return False
        if match_index > e.match_abs[self.slot, col]:
            e.match_abs[self.slot, col] = match_index
            if e.opts.eager_commit:
                # the ack path IS the commit tally now — no dirty mark:
                # a per-ack tick would re-reduce all [G] rows just to
                # find the commit this call already advanced (measured:
                # ack-driven ticks were ~2/3 of the loop's CPU at 1024
                # regions under write load).  Deadline-driven work
                # (beats, elections, snapshots) wakes the tick loop on
                # its own clock, and set_conf/role transitions keep
                # their explicit mark_dirty — a conf shrink that
                # advances the quorum without a new ack still gets its
                # discovery tick from set_conf's own mark.
                return e.eager_commit_slot(self.slot)
            e.mark_dirty()
        return False

    def update_conf(self, conf: Configuration, old_conf: Configuration) -> None:
        self._engine.set_conf(self.slot, conf, old_conf)

    def close(self) -> None:
        self._engine.release(self)

    # -- follower side -------------------------------------------------------

    def set_last_committed_index(self, index: int) -> bool:
        if self.pending_index != 0:
            return False
        if index <= self.last_committed_index:
            return False
        self.last_committed_index = index
        self._on_committed(index)
        return True

    # engine callback
    def _advance(self, new_commit: int) -> None:
        if self.pending_index == 0:
            return
        if new_commit > self.last_committed_index:
            self.last_committed_index = new_commit
            self._on_committed(new_commit)


class EngineControl:
    """Per-node handle to the engine's device control plane.

    Replaces, for engine-backed nodes, the reference's per-group timers
    and scalar tallies (SURVEY §3.1 "Timers & queues", §4.3):

      electionTimer/voteTimer  -> elect_deadline[g] + election_due mask
      vote tally (_VoteCtx)    -> granted[g,:] + elected mask
      stepDownTimer/_peer_acks -> last_ack[g,:] + step_down/lease masks
      heartbeat timers/hub tick-> hb_deadline[g] + hb_due mask

    Pre-vote tallies stay host-side scalars by design: the device role
    encoding has no pre-vote state (tpuraft.ops.tick) — pre-vote is a
    rare, transient probe that never mutates durable terms.

    One-off scalar queries (lease_valid for a single read, dead-quorum
    re-verification under the node lock) compute host-side from the SAME
    engine rows the device reduces — one [P] row, not a second copy of
    the state.
    """

    drives_heartbeats = True
    drives_snapshots = True
    # the device tick tallies SAFE ReadIndex rounds (fence_ok lane):
    # ReadConfirmBatcher checks this to skip its host-side per-ack set
    drives_read_fences = True

    def __init__(self, engine: "MultiRaftEngine", node, box: TpuBallotBox):
        self.engine = engine
        self.node = node
        self.slot = box.slot
        opts = node.options
        self._eto_ms = opts.election_timeout_ms
        # the lease is per-NODE (eto x ratio): the engine-wide lease_ms
        # param only feeds the device lease_valid mask, and a node whose
        # eto is shorter than the engine's must not inherit a lease
        # longer than its own election timeout (stale LEASE_BASED reads).
        # The (1 - rho) factor is the clock-drift safety margin (ISSUE
        # 18): the quorum granted us eto*ratio on THEIR clocks; ours may
        # run up to rho fast, so we only trust that fraction of it.
        self._lease_ms = int(self._eto_ms
                             * opts.raft_options.leader_lease_time_ratio
                             * (1.0 - opts.raft_options.clock_drift_bound))
        self._jitter_range = max(1, min(opts.raft_options.max_election_delay_ms,
                                        self._eto_ms))
        self._jitter = random.randrange(self._jitter_range)
        self._scheduled: set = set()
        # quiescence ("hibernate raft") state
        self._quiesce_after = opts.raft_options.quiesce_after_rounds
        self._quiesce_streak = 0
        self._quiesce_await: Optional[set] = None   # peers yet to ack
        self._lease_eps: list[str] = []   # leader: endpoints on the lease
        self._lease_src: Optional[str] = None  # follower: leader's store
        snap_ms = 0
        if opts.snapshot_uri and opts.snapshot.interval_secs > 0:
            snap_ms = opts.snapshot.interval_secs * 1000
        eff = engine.register_ctrl(
            self, node.server_id,
            eto_ms=self._eto_ms,
            hb_ms=max(1, self._eto_ms
                      // opts.raft_options.election_heartbeat_factor),
            lease_ms=int(self._eto_ms
                         * opts.raft_options.leader_lease_time_ratio
                         * (1.0 - opts.raft_options.clock_drift_bound)),
            snapshot_ms=snap_ms)
        if eff != self._eto_ms:
            self._adopt_eto(eff)

    def _adopt_eto(self, eff_eto_ms: int) -> None:
        """The engine's density floor raised this group's effective
        election timeout: adopt it host-side too, so RPC budgets, the
        follower leader-contact lease and jitter all agree with the
        device rows (a host lease shorter than the device timeout would
        re-open the vote guards long before any deadline can fire)."""
        opts = self.node.options
        if eff_eto_ms != opts.election_timeout_ms:
            LOG.info("%s: density floor raised election timeout "
                     "%dms -> %dms", self.node,
                     opts.election_timeout_ms, eff_eto_ms)
            opts.election_timeout_ms = eff_eto_ms
        self._eto_ms = eff_eto_ms
        self._lease_ms = int(eff_eto_ms
                             * opts.raft_options.leader_lease_time_ratio)
        self._jitter_range = max(1, min(
            opts.raft_options.max_election_delay_ms, eff_eto_ms))
        self._jitter = min(self._jitter, self._jitter_range - 1)

    # -- scheduling plumbing (engine tick -> node slow path) -----------------

    def schedule(self, name: str, handler) -> None:
        """Fire-and-dedupe: at most one outstanding handler per event
        kind — the tick may re-emit a mask for several ticks before the
        async handler flips the role."""
        if name in self._scheduled:
            return
        self._scheduled.add(name)

        async def run():
            try:
                await handler()
            except Exception:  # noqa: BLE001 — one group's handler only
                LOG.exception("engine event %s for %s failed",
                              name, self.node)
            finally:
                self._scheduled.discard(name)

        asyncio.ensure_future(run())

    def push_election_deadline(self, now_ms: Optional[int] = None,
                               new_jitter: bool = True) -> None:
        if now_ms is None:
            now_ms = self.engine.now_ms()
        if new_jitter:
            self._jitter = random.randrange(self._jitter_range)
        self.engine.elect_deadline[self.slot] = (
            now_ms + self._eto_ms + self._jitter)

    # -- node-facing API (mirrors TimerControl in tpuraft.core.node) ---------

    def start_follower(self) -> None:
        e = self.engine
        self._clear_quiesce_state()
        e.role[self.slot] = ROLE_FOLLOWER
        self.push_election_deadline()
        e.mark_dirty()

    def note_leader_contact(self) -> None:
        """Hot path (every AppendEntries): push the election deadline.
        Reuses the cached jitter — no RNG per append."""
        self.engine.elect_deadline[self.slot] = (
            self.engine.now_ms() + self._eto_ms + self._jitter)

    def on_candidate(self) -> None:
        e = self.engine
        self._clear_quiesce_state()
        e.role[self.slot] = ROLE_CANDIDATE
        self.push_election_deadline()   # vote-round timeout
        e.mark_dirty()

    def stop_vote_wait(self) -> None:
        pass  # deadline is inert once the role leaves CANDIDATE

    def start_vote_round(self) -> bool:
        """Clear the vote row, grant self.  Returns True when self alone
        is a quorum (single-voter group) — the engine's elected mask
        handles the multi-voter async case."""
        e = self.engine
        e.granted[self.slot, :] = False
        col = e.peer_col(self.slot, self.node.server_id)
        if col is not None:
            e.granted[self.slot, col] = True
        e.mark_dirty()
        return self.vote_quorum_now()

    def grant_vote(self, peer: PeerId) -> bool:
        """Record a granted vote.  Always returns False: the tally is the
        device tick's elected mask (-> Node._on_engine_elected)."""
        e = self.engine
        col = e.peer_col(self.slot, peer)
        if col is not None:
            e.granted[self.slot, col] = True
            e.mark_dirty()
        return False

    def vote_quorum_now(self) -> bool:
        """Host-side row check of the SAME granted/voter rows the device
        reduces — used to confirm `elected` under the node lock."""
        e, s = self.engine, self.slot
        g, vm, ovm = e.granted[s], e.voter_mask[s], e.old_voter_mask[s]

        def ok(mask):
            n = int(mask.sum())
            return n > 0 and int((g & mask).sum()) >= n // 2 + 1

        return ok(vm) and (not ovm.any() or ok(ovm))

    def on_leader(self) -> None:
        e, s = self.engine, self.slot
        now = e.now_ms()
        self._clear_quiesce_state()
        e.role[s] = ROLE_LEADER
        # grace period (reference: becomeLeader resets the replicators'
        # lastRpcSendTimestamp): every peer counts as freshly acked, so
        # dead-quorum step-down fires one full election timeout later,
        # not instantly on a fresh leader with silent followers
        e.last_ack[s, :] = now
        e.hb_deadline[s] = now       # beat on the next tick
        # periodic stepdown/priority cadence (the reference's
        # stepDownTimer at eto/2): first check one half-timeout out
        e.stepdown_deadline[s] = now + max(1, self._eto_ms // 2)
        e.granted[s, :] = False
        e.mark_dirty()

    def on_step_down(self, was_candidate: bool, was_leader: bool) -> None:
        self._clear_quiesce_state()
        self.engine.granted[self.slot, :] = False

    def on_follower(self) -> None:
        self.start_follower()

    # -- ack bookkeeping (replaces Node._peer_acks) --------------------------

    def record_ack(self, peer: PeerId, when: float) -> None:
        e = self.engine
        col = e.peer_col(self.slot, peer)
        if col is not None:
            ms = e.to_ms(when)
            if ms > e.last_ack[self.slot, col]:
                e.last_ack[self.slot, col] = ms
                # acks deliberately don't wake the tick (eager_commit
                # note in TpuBallotBox.commit_at) — EXCEPT while a read
                # fence is pending: its resolution IS this tick's q_ack
                # reduction, so the ack that completes the fence quorum
                # must drive a tick instead of waiting out a deadline
                if e.fence_start[self.slot] > _NEG_I32:
                    e.mark_dirty()

    # -- device read-fence plane (ReadConfirmBatcher rounds) -----------------

    def arm_read_fence(self, fence) -> None:
        """Register a pending SAFE ReadIndex round: the device tick's
        fence_ok lane calls ``fence.note_quorum()`` once the fused q_ack
        reduction reaches the round's start time.  ``fence`` needs
        ``note_quorum()`` and a ``done`` property (store_engine's
        _GroupFence); round-timeout cleanup stays with the caller."""
        self.engine.arm_read_fence(self.slot, fence)

    def _quorum_ack_ms(self) -> int:
        """q-th newest voter ack (joint-consensus aware), host-side from
        the engine row.  Counts self as acked now."""
        e, s = self.engine, self.slot
        now = e.now_ms()
        col = e.peer_col(s, self.node.server_id)
        row = e.last_ack[s].copy()
        if col is not None:
            row[col] = now

        def q_ack(mask):
            vals = np.sort(row[mask])[::-1]
            n = vals.size
            return int(vals[n // 2]) if n else _NEG_I32

        q = q_ack(e.voter_mask[s])
        if e.old_voter_mask[s].any():
            q = min(q, q_ack(e.old_voter_mask[s]))
        return q

    def quorum_ack_age_s(self) -> float:
        q = self._quorum_ack_ms()
        if q <= _NEG_I32:
            return float("inf")
        return max(0.0, (self.engine.now_ms() - q) / 1000.0)

    def lease_valid(self) -> bool:
        # a suspect local clock invalidates every timing argument the
        # lease rests on: fail closed (reads fall back to SAFE quorum
        # confirmation, which is clock-independent) — ISSUE 18
        sentinel = self.node.options.clock_sentinel
        if sentinel is not None and not sentinel.lease_check():
            return False
        e = self.engine
        # device lane fast path: the last tick's fused q_ack reduction
        # (ops/tick.py lease_valid lane) is a LOWER bound on the current
        # quorum-ack time — acks only ever arrive — so a lease check
        # that passes against it is sound without copying+sorting the
        # [P] row per read.  A miss (stale row, ack between ticks, or
        # genuinely expired) falls back to the exact host-side check.
        q = int(e.tick_q_ack[self.slot])
        if q > _NEG_I32 and e.now_ms() - q < self._lease_ms:
            e.lease_lane_hits += 1
            return True
        e.lease_lane_misses += 1
        if (e.now_ms() - self._quorum_ack_ms()
                < self._lease_ms):
            return True
        # quiescent leader: its per-group ack stream is suppressed, so
        # the store-level lease IS the leader lease (LEASE_BASED reads /
        # dead-quorum re-verification consult it through here).  The
        # rows are normally refreshed by note_store_ack, but an ack
        # landing between ticks must not fail a read spuriously.
        return self.is_quiescent() and self.store_lease_quorum_ok()

    def alive_peers(self) -> list[PeerId]:
        e, s = self.engine, self.slot
        horizon = e.now_ms() - self._eto_ms
        out = []
        for peer in self.node.list_peers():
            if peer == self.node.server_id:
                out.append(peer)
                continue
            col = e.peer_col(s, peer)
            if col is not None and e.last_ack[s, col] > horizon:
                out.append(peer)
        return out

    # -- quiescence ("hibernate raft") ---------------------------------------
    # A fully-replicated idle leader group hibernates after N consecutive
    # fully-acked beat rounds: the device masks skip it (hb_due /
    # election_due), its followers suppress election timeouts, and
    # liveness is delegated to ONE store-level lease beat per endpoint
    # pair (HeartbeatHub) — idle beat traffic collapses from O(G x P)
    # rows to O(stores^2) RPCs.  Any apply / conf change / vote request /
    # incoming entries instantly wakes the group; a store-lease expiry
    # wakes its dependents with randomized election timeouts.

    def is_quiescent(self) -> bool:
        return bool(self.engine.quiescent[self.slot])

    def note_activity(self) -> None:
        """Hot-path hook on protocol activity (apply staged, vote
        request, entries received): one array read when awake."""
        if self.engine.quiescent[self.slot]:
            self.wake_from_quiescence("activity")

    def _hub(self):
        nm = self.node.node_manager
        return None if nm is None else nm.heartbeat_hub

    def maybe_quiesce(self, now: int) -> None:
        """Called by the engine on every hb_due round for this (awake,
        leader) slot: track the idle streak; at the threshold this
        round's beats carry the quiesce handshake (hub.pulse reads the
        per-replicator intent), and the group hibernates only once
        EVERY follower acked — a refusal keeps it active, because a
        follower with a live election timer must keep receiving beats."""
        if self._quiesce_after <= 0 or self.engine.quiescent[self.slot]:
            return
        if not self._quiesce_eligible(now):
            self._quiesce_streak = 0
            self._quiesce_await = None
            return
        self._quiesce_streak += 1
        if self._quiesce_streak < self._quiesce_after:
            return
        reps = self.node.replicators.all()
        if not reps:
            # single-voter group: nobody to hand-shake, no lease needed
            # (its own self-ack keeps step_down quiet) — hibernate now
            self._finalize_quiesce()
            return
        self._quiesce_await = {r.peer for r in reps}
        for r in reps:
            r._quiesce_lease_ms = self._eto_ms

    def _quiesce_eligible(self, now: int) -> bool:
        """No pending appends, full match at the tail, not mid-change,
        every voter freshly acked — the 'provably idle' predicate."""
        node = self.node
        if node.node_manager is None or node.state.name != "LEADER":
            return False
        if node._conf_ctx is not None:
            return False
        e, s = self.engine, self.slot
        if e.old_voter_mask[s].any():
            return False
        tail = node.log_manager.last_log_index()
        if node.ballot_box.last_committed_index != tail:
            return False
        reps = node.replicators.all()
        for r in reps:
            if (not r._matched or r.retiring or r.match_index < tail
                    or not r.peer_multi_hb):
                return False
        if reps:
            # every voter acked within the last two beat intervals
            horizon = now - 2 * int(e.hb_ms[s]) - 50
            row, mask = e.last_ack[s], e.voter_mask[s].copy()
            col = int(e.self_col[s])
            if 0 <= col < mask.size:
                mask[col] = False
            if mask.any() and bool((row[mask] < horizon).any()):
                return False
        return True

    def note_quiesce_ack(self, peer: PeerId) -> None:
        """A follower acked a quiesce-handshake beat."""
        aw = self._quiesce_await
        if aw is None:
            return
        aw.discard(peer)
        if not aw:
            self._quiesce_await = None
            self._finalize_quiesce()

    def abort_quiesce(self) -> None:
        """A follower refused (or the fast path fell back): stay active."""
        self._quiesce_await = None
        self._quiesce_streak = 0

    def _finalize_quiesce(self) -> None:
        e, s = self.engine, self.slot
        node = self.node
        if e.quiescent[s] or node.node_manager is None:
            return
        if not self._quiesce_eligible(e.now_ms()):
            # an apply raced the handshake acks: stay active
            self._quiesce_streak = 0
            return
        e.quiescent[s] = True
        e.quiesce_events += 1
        # coalesced: a hibernation sweep at region density flips
        # thousands of groups at once — per-group rows would evict the
        # whole ring (the steady trickle keeps its per-group detail)
        _RECORDER.record_coalesced("quiesce", node.group_id,
                                   per_group=False,
                                   node=str(node.server_id),
                                   role="leader")
        hub = node.node_manager.heartbeat_hub
        hub.groups_quiesced += 1
        eps = sorted({r.peer.endpoint for r in node.replicators.all()})
        self._lease_eps = eps
        src = node.server_id.endpoint
        for ep in eps:
            hub.lease_add(ep, e, node.transport, src, self._eto_ms)
        e.note_quiesce_leader(s)

    def enter_quiescent_follower(self, leader_endpoint: str,
                                 lease_ms: int) -> bool:
        """The leader proposed hibernation via a quiesce beat and this
        node matched its row at the tail: suppress the election timeout
        and ride the leader store's liveness lease instead."""
        node = self.node
        e, s = self.engine, self.slot
        if node.node_manager is None:
            return False
        if e.quiescent[s]:
            return True
        e.quiescent[s] = True
        e.quiesce_events += 1
        _RECORDER.record_coalesced("quiesce", node.group_id,
                                   per_group=False,
                                   node=str(node.server_id),
                                   role="follower", src=leader_endpoint)
        self._lease_src = leader_endpoint
        hub = node.node_manager.heartbeat_hub
        hub.groups_quiesced += 1
        hub.lease_depend(leader_endpoint, self, lease_ms or self._eto_ms)
        return True

    def wake_from_quiescence(self, reason: str = "activity",
                             lease_expired: bool = False) -> None:
        e, s = self.engine, self.slot
        if not e.quiescent[s]:
            return
        _RECORDER.record_coalesced("wake", self.node.group_id,
                                   per_group=False,
                                   node=str(self.node.server_id),
                                   reason=reason)
        now = e.now_ms()
        # a follower waking under a FRESH store lease (e.g. a vote
        # solicitation from a restarted peer) must carry the delegated
        # liveness proof back into the per-group guard: clearing the
        # quiescent state kills quiescent_leader_alive(), and the raw
        # _last_leader_timestamp went stale by design while hibernating
        # — without this refresh the vote guards would swing open the
        # moment a group wakes, letting one restarted store depose
        # every healthy hibernating leader it pre-votes against
        leader_alive = self.quiescent_leader_alive()
        self._clear_quiesce_state()
        if leader_alive:
            self.node._last_leader_timestamp = self.node._clock.monotonic()
        if e.role[s] == ROLE_LEADER:
            e.hb_deadline[s] = now   # beat NOW; followers wake on it
        else:
            self._jitter = random.randrange(self._jitter_range)
            # store-lease expiry wakes WHOLE stores' worth of groups at
            # once: spread their elections over an extra full timeout so
            # the herd stays under the host's election capacity
            extra = random.randrange(self._eto_ms) if lease_expired else 0
            e.elect_deadline[s] = now + self._eto_ms + self._jitter + extra
        e.mark_dirty()

    def wake_for_lease_expiry(self) -> None:
        """Hub lease watcher: the store this group's (quiescent) leader
        lives on went silent past its lease — resume fault detection."""
        self.wake_from_quiescence("store-lease-expiry", lease_expired=True)

    def _clear_quiesce_state(self) -> None:
        e, s = self.engine, self.slot
        was = bool(e.quiescent[s])
        e.quiescent[s] = False
        self._quiesce_streak = 0
        self._quiesce_await = None
        hub = self._hub()
        if self._lease_eps:
            e.note_wake_leader(s)
            if hub is not None:
                for ep in self._lease_eps:
                    hub.lease_remove(ep, e)
            self._lease_eps = []
        if self._lease_src is not None:
            if hub is not None:
                hub.lease_undepend(self._lease_src, self)
            self._lease_src = None
        if was:
            e.wake_events += 1
            if hub is not None:
                hub.groups_woken += 1

    def quiescent_leader_alive(self) -> bool:
        """Follower-side vote-guard consult: while hibernating, 'my
        leader is alive' means 'its store's lease is fresh' — the
        per-group leader-contact timestamp legitimately goes stale."""
        e, s = self.engine, self.slot
        if not e.quiescent[s] or self._lease_src is None:
            return False
        hub = self._hub()
        return hub is not None and hub.lease_fresh(self._lease_src)

    def store_lease_quorum_ok(self) -> bool:
        """Leader-side lease-read consult for a QUIESCENT group: fresh
        store-lease acks must cover a voter quorum (the per-group ack
        stream is suppressed, so the store lease IS the leader lease)."""
        node = self.node
        hub = self._hub()
        if hub is None:
            return False
        voters = node.list_peers()
        if not voters:
            return False
        ok = sum(1 for p in voters
                 if p == node.server_id
                 or hub.lease_ack_fresh(p.endpoint, self._lease_ms))
        return ok >= len(voters) // 2 + 1

    # -- lifecycle -----------------------------------------------------------

    def deactivate(self) -> None:
        self._clear_quiesce_state()
        self.engine.role[self.slot] = ROLE_INACTIVE

    def shutdown(self) -> None:
        self.deactivate()
        self.engine.unregister_ctrl(self.slot)


class _NpOutputs:
    """numpy TickOutputs twin (backend="numpy" fallback)."""

    __slots__ = ("commit_rel", "commit_advanced", "elected", "election_due",
                 "step_down", "hb_due", "lease_valid", "snap_due", "q_ack",
                 "stepdown_due", "fence_ok")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class MultiRaftEngine:
    """Per-process batched consensus plane.  Start once, register each
    node's ballot box through :meth:`ballot_box_factory`."""

    def __init__(self, opts: Optional[TickOptions] = None):
        self.opts = opts or TickOptions()
        g, p = self.opts.max_groups, self.opts.max_peers
        self.G, self.P = g, p
        # numpy mirrors (host-owned truth between ticks) — commit plane.
        # Every [G]-leading row below is a LANE under graftcheck's
        # lane-coverage rule: it must be handled at _grow (pad), release
        # (slot reset), set_conf (conf re-map/invalidation) and
        # _maybe_time_rebase (time epoch shift), or carry a reasoned
        # `# lane: no-<site>` waiver here on its declaration.
        # lane: no-shift — log-index domain (rebased by _rebase, not the
        # time epoch)
        self.match_abs = np.zeros((g, p), np.int64)
        # lane: no-conf no-shift — per-group log base, conf-independent
        self.base = np.zeros(g, np.int64)
        # lane: no-conf no-shift — leadership window, reset by
        # reset_pending_index on role transitions; log-index domain
        self.pending_rel = np.ones(g, np.int32)
        self.voter_mask = np.zeros((g, p), bool)    # lane: no-shift — bool mask
        self.old_voter_mask = np.zeros((g, p), bool)  # lane: no-shift — bool mask
        # lane: no-conf no-shift — absolute committed index; a conf
        # change never moves what is already committed
        self.commit_abs = np.zeros(g, np.int64)
        # protocol plane (SURVEY §8.1): roles, deadlines, acks, votes
        # lane: no-conf no-shift — host-applied role transitions only
        # (set_conf never changes who leads); not time-valued
        self.role = np.full(g, ROLE_INACTIVE, np.int32)
        # lane: no-conf — deadlines re-arm on role transitions and leader
        # contact, not on membership changes
        self.elect_deadline = np.zeros(g, np.int64)
        # lane: no-conf — beat cadence is role-driven; set_conf's fresh
        # peers get their grace stamp through last_ack instead
        self.hb_deadline = np.zeros(g, np.int64)
        self.last_ack = np.full((g, p), _NEG_I32, np.int64)
        self.granted = np.zeros((g, p), bool)   # lane: no-shift — bool votes
        # lane: no-shift — column index, not time-valued
        self.self_col = np.full(g, -1, np.int32)
        # lane: no-conf no-shift — registration bit (register_ctrl /
        # unregister_ctrl own it); not time-valued
        self.has_ctrl = np.zeros(g, bool)
        # quiescence ("hibernate raft"): a True row suppresses the
        # group's hb_due/election_due masks on device; liveness rides
        # the store-level lease (HeartbeatHub).  Host-owned like role.
        # lane: no-conf no-shift — set_conf wakes a hibernating group
        # THROUGH EngineControl.wake_from_quiescence (which clears this
        # row and the hub lease bookkeeping together — a bare row write
        # here would leak the lease); not time-valued
        self.quiescent = np.zeros(g, bool)
        # read plane: the last tick's fused q_ack reduction ([G] q-th
        # newest voter ack, ms).  Acks only ever arrive, so a stale row
        # is a LOWER bound on the true quorum-ack time — a lease check
        # that passes against it is sound, and one that fails falls back
        # to the exact host-side [P] sort (EngineControl.lease_valid).
        self.tick_q_ack = np.full(g, _NEG_I32, np.int64)
        self.lease_lane_hits = 0     # lease reads answered off the row
        self.lease_lane_misses = 0   # fell back to the host-side sort
        # witness voters (either config): metadata-only replicas — they
        # vote and ack, but the device commit reduce clamps to the best
        # DATA-replica match (ballot.witness_commit_clamp).
        # lane: no-shift — bool mask
        self.witness_mask = np.zeros((g, p), bool)
        self._n_witness_slots = 0    # steady-state clamp skip when zero
        # periodic stepdown/priority lane (the reference's stepDownTimer,
        # eto/2): fires Node._check_dead_nodes for engine leaders —
        # dead-quorum re-verification AND priority_transfer_rounds
        # accrual (decay-elected leaders hand leadership back).
        # lane: no-conf — re-armed on leadership transitions (on_leader)
        # and every fire, never by membership changes
        self.stepdown_deadline = np.zeros(g, np.int64)
        self.stepdown_ticks = 0      # stepdown_due fires applied
        # device read-fence plane: earliest pending ReadConfirmBatcher
        # round start per slot (NEG = none); the tick's fence_ok lane
        # resolves rounds against the fused q_ack reduction instead of a
        # host-side per-ack set tally.
        self.fence_start = np.full(g, _NEG_I32, np.int64)
        self._fence_waiters: dict[int, list] = {}  # slot -> [(start, fence)]
        self.fence_lane_armed = 0    # rounds armed on the device lane
        self.fence_lane_resolves = 0  # rounds resolved by fence_ok
        # store-lease plumbing for QUIESCENT LEADER slots: endpoint ->
        # {slot: [cols]} of last_ack cells refreshed by one store-lease
        # ack from that endpoint (flattened index arrays cached per
        # endpoint) — dead-quorum step-down and leader-lease reads for
        # hibernating groups consult the store lease through these rows.
        self._lease_cols: dict[str, dict[int, list[int]]] = {}
        self._lease_arrays: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self.quiesce_events = 0   # groups that entered hibernation
        self.wake_events = 0      # groups woken (activity / lease expiry)
        self._peer_cols: list[dict[PeerId, int]] = [dict() for _ in range(g)]
        self._boxes: list[Optional[TpuBallotBox]] = [None] * g
        self._ctrls: list[Optional[EngineControl]] = [None] * g
        self._ctrl_server: list[Optional[PeerId]] = [None] * g
        self._free = list(range(g - 1, -1, -1))
        self._dirty = False
        self._dirty_event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._tick_fn = None  # jitted raft_tick outputs (None => numpy path)
        self._deadline_fold = None  # mesh mode: sharded earliest-deadline min
        self._params_dev = None
        self.ticks = 0
        self.commit_advances = 0
        # event-driven commit advancement (TickOptions.eager_commit):
        # quorums closed on the ack path by eager_commit_slot, without
        # waiting for the next device tick
        self.eager_commits = 0
        # device-tick profiling (fleet observability): per-tick wall
        # time attributed to the three phases every tick pays — host
        # state build, device dispatch (jit call + output transfer, or
        # the numpy twin), host apply (commit callbacks + protocol
        # scheduling).  Always on: four locked histogram updates per
        # TICK (not per op) — ticks are paced by their own cost, so
        # this stays noise even at max cadence.
        from tpuraft.util.metrics import Histogram
        self.tick_hists = {
            "tick_total_ms": Histogram(),
            "tick_build_ms": Histogram(),
            "tick_device_ms": Histogram(),
            "tick_apply_ms": Histogram(),
        }
        # --profile-ticks window: a dedicated Tracer capturing one span
        # per tick phase for the next N ticks (perfetto timeline export
        # through the trace plane's exporter); None = disarmed (the
        # hot-path cost is one attribute test per tick)
        self._tick_tracer = None
        self._tick_prof_left = 0
        # protocol params: [G] rows — each registered node's NodeOptions
        # timeouts apply to ITS groups only (mixed-timeout engines, e.g.
        # a PD group + region groups in one process, run correct
        # per-group constants; was engine-wide first-node-wins pre-r3).
        # lane: no-conf no-shift — registration-derived parameters
        # (register_ctrl + the density floor own them); they are
        # durations, not absolute times, so the epoch shift skips them
        self.eto_ms = np.full(g, _DEF_ETO_MS, np.int64)
        # lane: no-conf no-shift — same registration-derived duration row
        self.hb_ms = np.full(g, _DEF_HB_MS, np.int64)
        # lane: no-conf no-shift — same registration-derived duration row
        self.lease_ms = np.full(g, _DEF_LEASE_MS, np.int64)
        # density-aware timeout floors: the REQUESTED NodeOptions values
        # per slot; the effective rows above are max(requested, derived
        # floor) with hb/lease scaled proportionally.  The floor grows
        # with registered group count and the measured tick cost, so a
        # 16K-group process lands on a safe operating point without the
        # hand-tuned 60s timeouts BENCH_SCALE previously required.
        # lane: no-conf no-shift — requested durations (register_ctrl
        # writes them; conf changes and the time epoch never do)
        self.req_eto_ms = np.full(g, _DEF_ETO_MS, np.int64)
        # lane: no-conf no-shift — same requested-duration row
        self.req_hb_ms = np.full(g, _DEF_HB_MS, np.int64)
        # lane: no-conf no-shift — same requested-duration row
        self.req_lease_ms = np.full(g, _DEF_LEASE_MS, np.int64)
        self._floor_applied_ms = 0
        self._tick_cost_ema_s = 0.0
        # the floor derivation scans every registered slot, so it runs
        # only at geometric registration counts (the floor is ~linear
        # in n, and the apply gate already tolerates 25% staleness) —
        # a 16K-group boot pays O(G) total floor work, not O(G^2)
        self._n_ctrls = 0
        self._floor_cached_ms = 0
        self._floor_next_n = 0
        # engine-scheduled snapshot cadence (the reference's 4th timer,
        # snapshotTimer): [G] interval row (0 = disabled) + deadline row
        # replace G per-group RepeatedTimers; fires staggered by jitter.
        # lane: no-conf no-shift — interval duration owned by
        # register_ctrl; membership changes don't move the cadence
        self.snap_ms = np.zeros(g, np.int64)
        # lane: no-conf — snapshot cadence is registration-driven, not
        # membership-driven (the deadline row IS epoch-shifted)
        self.snap_deadline = np.zeros(g, np.int64)
        # injectable store clock (ISSUE 18): the engine's whole time
        # plane — deadlines, ack stamps, leases — runs on this clock,
        # so a ChaosClock skews the STORE exactly like a bad machine
        self._clock = clockmod.resolve(self.opts.clock)
        self._t0 = self._clock.monotonic()

    # -- time ----------------------------------------------------------------

    def now_ms(self) -> int:
        return int((self._clock.monotonic() - self._t0) * 1000)

    def to_ms(self, monotonic_time: float) -> int:
        return int((monotonic_time - self._t0) * 1000)

    def _maybe_time_rebase(self, now: int) -> None:
        """Shift the time epoch before int32 ms overflows (~12 days)."""
        if now < _TIME_REBASE_MS:
            return
        shift = now - int(self.eto_ms.max()) * 4
        self._t0 += shift / 1000.0
        self.elect_deadline -= shift
        self.hb_deadline -= shift
        self.snap_deadline -= shift
        self.stepdown_deadline -= shift
        np.maximum(self.last_ack - shift, _NEG_I32, out=self.last_ack)
        np.maximum(self.tick_q_ack - shift, _NEG_I32, out=self.tick_q_ack)
        # NEG rows stay NEG (no fence pending); armed rows shift with
        # the epoch like the ack stamps they are compared against
        np.maximum(self.fence_start - shift, _NEG_I32, out=self.fence_start)
        for waiters in self._fence_waiters.values():
            waiters[:] = [(max(start - shift, _NEG_I32 + 1), fence)
                          for start, fence in waiters]

    # -- registry ------------------------------------------------------------

    def ballot_box_factory(self):
        """Returns a factory usable as Node(ballot_box_factory=...)."""

        def make(on_committed: Callable[[int], None]) -> TpuBallotBox:
            slot = self.alloc_slot()
            box = TpuBallotBox(self, slot, on_committed)
            self._boxes[slot] = box
            return box

        return make

    def register_ctrl(self, ctrl: EngineControl, server_id: PeerId,
                      eto_ms: int, hb_ms: int, lease_ms: int,
                      snapshot_ms: int = 0) -> int:
        """Register a node's control plane.  Returns the EFFECTIVE
        election timeout for the slot — the requested value raised to the
        engine's density floor when the process hosts more groups than
        the requested timeout can beat within the cpu budget."""
        s = ctrl.slot
        self._ctrls[s] = ctrl
        self._ctrl_server[s] = server_id
        self.has_ctrl[s] = True
        col = self._peer_cols[s].get(server_id)
        self.self_col[s] = -1 if col is None else col
        self.req_eto_ms[s], self.req_hb_ms[s], self.req_lease_ms[s] = \
            eto_ms, hb_ms, lease_ms
        self._n_ctrls += 1
        if self._n_ctrls >= self._floor_next_n:
            self._floor_cached_ms = self._density_floor_ms()
            self._floor_next_n = int(self._n_ctrls * 1.25) + 1
        floor = self._floor_cached_ms
        if floor > self._floor_applied_ms * 1.25:
            # the floor grew materially (more groups / slower ticks):
            # re-derive every controlled slot's effective rows.  Gated
            # to >25% growth so a 16K-registration boot costs O(G log G)
            # row rewrites, not O(G^2).
            self._floor_applied_ms = floor
            self._reapply_floor()
        else:
            self._apply_floor_slot(s)
        self.snap_ms[s] = snapshot_ms
        if snapshot_ms > 0:
            # first due staggered over [0.5, 1.5) intervals: groups
            # registered together must not snapshot as one herd
            self.snap_deadline[s] = self.now_ms() + int(
                snapshot_ms * (0.5 + random.random()))
        self._params_dev = None  # (re)built at next device tick
        return int(self.eto_ms[s])

    # -- density-aware timeout floors ---------------------------------------

    def _density_floor_ms(self) -> int:
        """Minimum safe election timeout at the CURRENT registered
        density, derived from group count and measured costs instead of
        operator hand-tuning.  Two terms:

        - beat-budget: idle beats/s = groups x followers x factor /
          eto_s; each beat costs ~``beat_cost_us`` end to end, and the
          idle beat plane may use at most ``beat_cpu_budget`` of one
          core — solve for eto.
        - tick-cost: one heartbeat interval must dwarf a measured tick
          dispatch (x50), or the engine cannot keep every group's beat
          schedule — a tunneled/slow device raises the floor on its own.
        """
        if not self.opts.density_aware_timeouts:
            return 0
        n = int(self.has_ctrl.sum())
        if n == 0:
            return 0
        vm = self.voter_mask[self.has_ctrl]
        per = np.clip(vm.sum(axis=1) - 1, 0, None)
        followers = float(per.mean()) if per.size else 2.0
        req_eto = self.req_eto_ms[self.has_ctrl].astype(np.float64)
        req_hb = np.maximum(self.req_hb_ms[self.has_ctrl], 1)
        factor = float((req_eto / req_hb).mean()) if req_eto.size else 10.0
        beat_term = (n * followers * factor * self.opts.beat_cost_us
                     / (max(self.opts.beat_cpu_budget, 1e-3) * 1000.0))
        tick_term = self._tick_cost_ema_s * 1000.0 * factor * 50.0
        return int(max(beat_term, tick_term))

    def _apply_floor_slot(self, s: int) -> None:
        floor = self._floor_applied_ms
        req = int(self.req_eto_ms[s])
        if req >= floor or floor <= 0:
            self.eto_ms[s] = self.req_eto_ms[s]
            self.hb_ms[s] = self.req_hb_ms[s]
            self.lease_ms[s] = self.req_lease_ms[s]
            return
        ratio = floor / max(req, 1)
        self.eto_ms[s] = floor
        self.hb_ms[s] = max(1, int(self.req_hb_ms[s] * ratio))
        self.lease_ms[s] = max(1, int(self.req_lease_ms[s] * ratio))

    def _reapply_floor(self) -> None:
        floor = self._floor_applied_ms
        changed = 0
        for s in np.nonzero(self.has_ctrl)[0]:
            before = int(self.eto_ms[s])
            self._apply_floor_slot(int(s))
            after = int(self.eto_ms[s])
            if after != before:
                changed += 1
                ctrl = self._ctrls[s]
                if ctrl is not None:
                    ctrl._adopt_eto(after)
        if changed:
            LOG.info("engine density floor %dms raised %d groups' "
                     "election timeouts (%d registered)",
                     floor, changed, int(self.has_ctrl.sum()))
        self._params_dev = None

    def unregister_ctrl(self, slot: int) -> None:
        # idempotent per REGISTRATION, not per call: a controlled node's
        # shutdown reaches here twice (EngineControl.shutdown, then
        # ballot_box.close -> release), and a bare commit-plane box
        # (drive_protocol off) releases without ever registering — an
        # unconditional decrement drifted _n_ctrls negative under churn,
        # and the density-floor recompute trigger (_n_ctrls >=
        # _floor_next_n in register_ctrl) could then stay silent while
        # the REAL controlled density grew past the safe operating point
        if self.has_ctrl[slot]:
            self._n_ctrls -= 1
        self._ctrls[slot] = None
        self._ctrl_server[slot] = None
        self.has_ctrl[slot] = False
        self.self_col[slot] = -1

    def alloc_slot(self) -> int:
        if not self._free:
            self._grow()
        return self._free.pop()

    def _grow(self) -> None:
        """Double group capacity in place.  Region splits mint new raft
        groups at runtime; a full engine must absorb them, not crash
        the new RegionEngine.  The next tick recompiles once for the
        new shape (jit caches per shape); doubling preserves
        divisibility by mesh_devices for the sharded path."""
        old_g = self.G
        new_g = old_g * 2

        def pad(a: np.ndarray, fill=0) -> np.ndarray:
            extra = np.full((old_g,) + a.shape[1:], fill, a.dtype)
            return np.concatenate([a, extra])

        self.match_abs = pad(self.match_abs)
        self.base = pad(self.base)
        self.pending_rel = pad(self.pending_rel, 1)
        self.voter_mask = pad(self.voter_mask)
        self.old_voter_mask = pad(self.old_voter_mask)
        self.commit_abs = pad(self.commit_abs)
        self.role = pad(self.role, ROLE_INACTIVE)
        self.elect_deadline = pad(self.elect_deadline)
        self.hb_deadline = pad(self.hb_deadline)
        self.last_ack = pad(self.last_ack, _NEG_I32)
        self.tick_q_ack = pad(self.tick_q_ack, _NEG_I32)
        self.witness_mask = pad(self.witness_mask)
        self.stepdown_deadline = pad(self.stepdown_deadline)
        self.fence_start = pad(self.fence_start, _NEG_I32)
        self.granted = pad(self.granted)
        self.self_col = pad(self.self_col, -1)
        self.has_ctrl = pad(self.has_ctrl)
        self.quiescent = pad(self.quiescent)
        self.eto_ms = pad(self.eto_ms, _DEF_ETO_MS)
        self.hb_ms = pad(self.hb_ms, _DEF_HB_MS)
        self.lease_ms = pad(self.lease_ms, _DEF_LEASE_MS)
        self.req_eto_ms = pad(self.req_eto_ms, _DEF_ETO_MS)
        self.req_hb_ms = pad(self.req_hb_ms, _DEF_HB_MS)
        self.req_lease_ms = pad(self.req_lease_ms, _DEF_LEASE_MS)
        self.snap_ms = pad(self.snap_ms)
        self.snap_deadline = pad(self.snap_deadline)
        self._params_dev = None  # [G] rows must match the grown shape
        self._peer_cols.extend(dict() for _ in range(old_g))
        self._boxes.extend([None] * old_g)
        self._ctrls.extend([None] * old_g)
        self._ctrl_server.extend([None] * old_g)
        self._free = list(range(new_g - 1, old_g - 1, -1))
        self.G = new_g
        LOG.info("engine grew: %d -> %d group slots", old_g, new_g)

    def release(self, box: TpuBallotBox) -> None:
        s = box.slot
        self._boxes[s] = None
        self.unregister_ctrl(s)
        self.voter_mask[s] = False
        self.old_voter_mask[s] = False
        self.match_abs[s] = 0
        self.commit_abs[s] = 0
        self.base[s] = 0
        self.pending_rel[s] = 1
        self.role[s] = ROLE_INACTIVE
        self.elect_deadline[s] = 0
        self.hb_deadline[s] = 0
        self.last_ack[s] = _NEG_I32
        self.tick_q_ack[s] = _NEG_I32
        if self.witness_mask[s].any():
            self._n_witness_slots -= 1
        self.witness_mask[s] = False
        self.stepdown_deadline[s] = 0
        self.fence_start[s] = _NEG_I32
        # pending fences die with the slot; the batcher round's timeout
        # sweep resolves their futures False
        self._fence_waiters.pop(s, None)
        self.granted[s] = False
        self.quiescent[s] = False
        self.note_wake_leader(s)
        self.eto_ms[s], self.hb_ms[s], self.lease_ms[s] = \
            _DEF_ETO_MS, _DEF_HB_MS, _DEF_LEASE_MS
        self.req_eto_ms[s], self.req_hb_ms[s], self.req_lease_ms[s] = \
            _DEF_ETO_MS, _DEF_HB_MS, _DEF_LEASE_MS
        self.snap_ms[s] = 0
        self.snap_deadline[s] = 0
        self._params_dev = None
        self._peer_cols[s].clear()
        self._free.append(s)

    def set_conf(self, slot: int, conf: Configuration,
                 old_conf: Configuration) -> None:
        """Map peers to columns and set voter masks for a group."""
        cols = self._peer_cols[slot]
        all_peers = list(dict.fromkeys(
            conf.peers + old_conf.peers + conf.learners + old_conf.learners))
        # retain existing column assignments; add new peers to free columns
        used = set(cols.values())
        for peer in all_peers:
            if peer not in cols:
                col = next((i for i in range(self.P) if i not in used), None)
                if col is None:
                    raise RuntimeError(
                        f"group slot {slot}: {len(all_peers)} distinct peers "
                        f"exceed max_peers={self.P} engine columns")
                cols[peer] = col
                used.add(col)
        # drop stale peers
        for peer in [p for p in cols if p not in all_peers]:
            self.match_abs[slot, cols[peer]] = 0
            self.last_ack[slot, cols[peer]] = _NEG_I32
            self.granted[slot, cols[peer]] = False
            del cols[peer]
        vm = np.zeros(self.P, bool)
        ovm = np.zeros(self.P, bool)
        wm = np.zeros(self.P, bool)
        for peer in conf.peers:
            vm[cols[peer]] = True
        for peer in old_conf.peers:
            ovm[cols[peer]] = True
        # witness columns (either config): the union mirrors the host
        # BallotBox clamp's data set `conf.data_peers + old_conf
        # .data_peers` — a column is data only if NEITHER config marks
        # it witness
        for peer in getattr(conf, "witnesses", ()) or ():
            if peer in cols:
                wm[cols[peer]] = True
        for peer in getattr(old_conf, "witnesses", ()) or ():
            if peer in cols:
                wm[cols[peer]] = True
        had_witness = bool(self.witness_mask[slot].any())
        self.voter_mask[slot] = vm
        self.old_voter_mask[slot] = ovm
        self.witness_mask[slot] = wm
        self._n_witness_slots += int(wm.any()) - int(had_witness)
        # the cached read-plane q_ack was reduced over the OLD voter set;
        # a shrunk conf can make it overstate the new quorum's freshness
        # (no longer a lower bound) — drop it until the next tick
        self.tick_q_ack[slot] = _NEG_I32
        # pending read fences were armed against the old voter set too:
        # drop the device lane for them (the batcher round's own timeout
        # resolves their futures; a conf change mid-round is rare)
        if self.fence_start[slot] > _NEG_I32:
            self.fence_start[slot] = _NEG_I32
            self._fence_waiters.pop(slot, None)
        if self.role[slot] == ROLE_LEADER:
            # grace window for peers ADDED mid-leadership (reference:
            # addReplicator stamps lastRpcSendTimestamp at start): a
            # never-acked NEG column would otherwise pin the joint q_ack
            # reduce at NEG_INF, which the have-ack gate reads as "no
            # data" — so a dead new config could never fire step_down.
            # Invariant: a leader's (old_)voter columns are never NEG.
            row = self.last_ack[slot]
            fresh = (vm | ovm) & (row <= _NEG_I32)
            if fresh.any():
                row[fresh] = self.now_ms()
        server = self._ctrl_server[slot]
        if server is not None:
            col = cols.get(server)
            self.self_col[slot] = -1 if col is None else col
        if self.quiescent[slot]:
            # a configuration change is protocol activity: a hibernating
            # group must wake to drive it (and its lease bookkeeping no
            # longer matches the new peer set)
            ctrl = self._ctrls[slot]
            if ctrl is not None:
                ctrl.wake_from_quiescence("conf-change")
        self.mark_dirty()

    def peer_col(self, slot: int, peer: PeerId) -> Optional[int]:
        return self._peer_cols[slot].get(peer)

    def mark_dirty(self) -> None:
        self._dirty = True
        self._dirty_event.set()

    # -- device read-fence plane (ReadConfirmBatcher rounds) -----------------

    def arm_read_fence(self, slot: int, fence) -> None:
        """Queue a SAFE ReadIndex round on the device tally: the round
        is confirmed once the fused q_ack reduction shows a voter quorum
        acked at-or-after *now*.  ``fence_start[slot]`` carries the
        EARLIEST pending round's start (a q_ack covering it covers every
        later round the resolve pass walks)."""
        start = self.now_ms()
        self._fence_waiters.setdefault(slot, []).append((start, fence))
        cur = self.fence_start[slot]
        self.fence_start[slot] = start if cur <= _NEG_I32 else min(cur, start)
        self.fence_lane_armed += 1
        self.mark_dirty()

    def discard_read_fence(self, slot: int, fence) -> None:
        """Drop one fence from the device lane (round end/timeout) and
        re-derive the row's earliest pending start.  Idempotent — a
        fence the resolve pass already removed just isn't found."""
        waiters = self._fence_waiters.get(slot)
        if not waiters:
            return
        keep = [(start, f) for start, f in waiters if f is not fence]
        if keep:
            self._fence_waiters[slot] = keep
            self.fence_start[slot] = min(start for start, _ in keep)
        else:
            self._fence_waiters.pop(slot, None)
            self.fence_start[slot] = _NEG_I32

    def _resolve_fences(self, s: int) -> None:
        """fence_ok fired for slot ``s``: confirm every pending round
        whose start the published q_ack covers, drop abandoned fences,
        re-arm the row to the earliest still-pending start."""
        waiters = self._fence_waiters.get(s)
        if not waiters:
            self.fence_start[s] = _NEG_I32
            return
        qa = int(self.tick_q_ack[s])
        keep = []
        for start, fence in waiters:
            if start <= qa:
                self.fence_lane_resolves += 1
                fence.note_quorum()
            elif not fence.done:
                keep.append((start, fence))
        if keep:
            self._fence_waiters[s] = keep
            self.fence_start[s] = min(start for start, _ in keep)
        else:
            self._fence_waiters.pop(s, None)
            self.fence_start[s] = _NEG_I32

    # -- store-lease plumbing (quiescent leader slots) -----------------------

    def note_quiesce_leader(self, slot: int) -> None:
        """A leader slot hibernated: its peers' last_ack cells are now
        refreshed from store-lease acks (one per endpoint per interval)
        instead of per-group beat acks."""
        self_col = int(self.self_col[slot])
        for peer, col in self._peer_cols[slot].items():
            if col == self_col:
                continue
            d = self._lease_cols.setdefault(peer.endpoint, {})
            d.setdefault(slot, []).append(col)
            self._lease_arrays.pop(peer.endpoint, None)

    def note_wake_leader(self, slot: int) -> None:
        for ep in list(self._lease_cols):
            if self._lease_cols[ep].pop(slot, None) is not None:
                self._lease_arrays.pop(ep, None)
                if not self._lease_cols[ep]:
                    del self._lease_cols[ep]

    def note_store_ack(self, endpoint: str,
                       when_ms: Optional[int] = None) -> None:
        """A store-lease ack from ``endpoint``: refresh every quiescent
        leader slot's last_ack cells toward it (vectorized — one fancy-
        indexed write per ack, not O(G) RPC bookkeeping).  Dead-quorum
        step-down and leader-lease reads then see a live quorum for
        hibernating groups exactly as long as the store lease flows."""
        d = self._lease_cols.get(endpoint)
        if not d:
            return
        arrs = self._lease_arrays.get(endpoint)
        if arrs is None:
            slots: list[int] = []
            cols: list[int] = []
            for s, cs in d.items():
                slots.extend([s] * len(cs))
                cols.extend(cs)
            arrs = (np.asarray(slots, np.int64), np.asarray(cols, np.int64))
            self._lease_arrays[endpoint] = arrs
        ms = self.now_ms() if when_ms is None else when_ms
        sl, co = arrs
        self.last_ack[sl, co] = np.maximum(self.last_ack[sl, co], ms)

    def describe(self) -> str:
        """Live engine state for operators (the device-plane counterpart
        of Node#describe)."""
        used = sum(1 for b in self._boxes if b is not None)
        return (f"MultiRaftEngine<G={self.G} P={self.P} used={used} "
                f"ctrl={int(self.has_ctrl.sum())} "
                f"backend={self.opts.backend} "
                f"mesh={self.opts.mesh_devices or 1} "
                f"ticks={self.ticks} commit_advances={self.commit_advances} "
                f"eager_commits={self.eager_commits} "
                f"leaders={int((self.role == ROLE_LEADER).sum())} "
                f"quiescent={int(self.quiescent.sum())} "
                f"quiesce_events={self.quiesce_events} "
                f"wake_events={self.wake_events} "
                f"lease_lane_hits={self.lease_lane_hits} "
                f"lease_lane_misses={self.lease_lane_misses} "
                f"witness_groups={self._n_witness_slots} "
                f"stepdown_ticks={self.stepdown_ticks} "
                f"fence_armed={self.fence_lane_armed} "
                f"fence_resolves={self.fence_lane_resolves} "
                f"eto_floor_ms={self._floor_applied_ms} "
                f"tick_p99_ms={self.tick_hists['tick_total_ms'].percentile(99):.3f}>")

    # -- device-tick profiling (fleet observability) -------------------------

    def tick_histograms(self) -> dict:
        """Per-tick phase wall-time histograms as snapshot dicts — the
        shape ``prometheus_text(histograms=...)`` renders (served by
        StoreEngine.metrics_text for engine-backed stores)."""
        return {k: h.snapshot() for k, h in self.tick_hists.items()}

    def lane_stats(self) -> dict:
        """[G]-lane occupancy gauges, computed as vectorized reductions
        over the host mirrors the tick already owns — no per-group
        Python.  ``hibernation_fraction`` is quiescent/controlled (the
        number the PD's ClusterView aggregates fleet-wide)."""
        hc = self.has_ctrl
        n = int(hc.sum())
        leaders = int(((self.role == ROLE_LEADER) & hc).sum())
        quiescent = int((self.quiescent & hc).sum())
        stats = {
            "groups": n,
            "leaders": leaders,
            "candidates": int(((self.role == ROLE_CANDIDATE) & hc).sum()),
            "followers": int(((self.role == ROLE_FOLLOWER) & hc).sum()),
            "quiescent": quiescent,
            "hibernation_fraction": round(quiescent / n, 4) if n else 0.0,
            "tick_cost_ema_ms": round(self._tick_cost_ema_s * 1e3, 3),
            "witness_groups": self._n_witness_slots,
            "stepdown_ticks": self.stepdown_ticks,
            "fence_lane_armed": self.fence_lane_armed,
            "fence_lane_resolves": self.fence_lane_resolves,
            "fences_pending": sum(len(w) for w
                                  in self._fence_waiters.values()),
        }
        # q_ack distribution: age of the quorum-newest ack per AWAKE
        # leader row (quiescent leaders ride the store lease; their rows
        # age by design and would drown the signal) — the read plane's
        # lease headroom at a glance
        lead = (self.role == ROLE_LEADER) & hc & ~self.quiescent
        qa = self.tick_q_ack[lead]
        qa = qa[qa > _NEG_I32]
        if qa.size:
            ages = np.clip(self.now_ms() - qa, 0, None)
            stats["q_ack_age_ms_p50"] = float(np.percentile(ages, 50))
            stats["q_ack_age_ms_p99"] = float(np.percentile(ages, 99))
            stats["q_ack_age_ms_max"] = float(ages.max())
        else:
            stats["q_ack_age_ms_p50"] = 0.0
            stats["q_ack_age_ms_p99"] = 0.0
            stats["q_ack_age_ms_max"] = 0.0
        return stats

    def profile_ticks(self, n: int) -> None:
        """Arm a profiling window: the next ``n`` ticks each record a
        root span + build/device/apply phase spans into a dedicated
        tracer (sample_rate=1, no slow trigger), exportable as a
        perfetto timeline via :meth:`export_tick_timeline`.  Disarmed
        (the steady state) the tick pays one attribute test."""
        from tpuraft.util.trace import Tracer

        if n <= 0:
            self._tick_tracer = None
            self._tick_prof_left = 0
            return
        self._tick_tracer = Tracer().configure(
            enabled=True, sample_rate=1.0, seed=0,
            ring=max(4096, 4 * n + 8), slow_trigger=False)
        self._tick_prof_left = n

    def _profile_tick(self, t0: float, t1: float, t2: float, t3: float,
                      advanced: int) -> None:
        # direct-emit path (odd tid = "record unconditionally"): the
        # spans carry their own measured [t0,t1] intervals, so staging
        # through begin_op/end_op would mis-stamp the root.  One tid
        # for the whole window keeps every tick on one perfetto track,
        # with the phase spans nesting inside each tick span.
        tr = self._tick_tracer
        tid = 1
        tr.span(tid, "tick", t0, t3, proc="engine", seq=self.ticks,
                advanced=advanced,
                groups=int(self.has_ctrl.sum()),
                quiescent=int((self.quiescent & self.has_ctrl).sum()))
        tr.span(tid, "tick_build", t0, t1, proc="engine")
        tr.span(tid, "tick_device", t1, t2, proc="engine")
        tr.span(tid, "tick_apply", t2, t3, proc="engine")
        self._tick_prof_left -= 1
        if self._tick_prof_left <= 0:
            self._tick_prof_left = 0
            # keep the tracer for export; stop recording
            self._tick_tracer, self._tick_trace_done = None, tr

    def export_tick_timeline(self, path: str) -> int:
        """Write the captured (or in-flight) --profile-ticks window as
        perfetto-loadable chrome trace JSON; returns the span count
        (0 = no window was armed)."""
        tr = self._tick_tracer or getattr(self, "_tick_trace_done", None)
        if tr is None:
            return 0
        return tr.export_chrome(path)

    # -- tick loop -----------------------------------------------------------

    def _resolve_backend(self) -> str:
        """backend="auto": the jax device plane exists FOR accelerators —
        on a CPU-only host the vectorized numpy twin of the tick beats
        XLA-CPU dispatch overhead at any G that fits one box (profiled:
        per-tick jit call overhead dominated small-G CPU ticks).  A mesh
        request always means jax."""
        b = self.opts.backend
        if b != "auto":
            return b
        if self.opts.mesh_devices and self.opts.mesh_devices > 1:
            return "jax"
        try:
            import jax

            return "jax" if jax.default_backend() != "cpu" else "numpy"
        except Exception:  # noqa: BLE001 — no jax at all
            return "numpy"

    async def start(self) -> None:
        if self._resolve_backend() != "numpy":
            import jax

            from tpuraft.ops.tick import raft_tick_outputs_jit

            if self.opts.mesh_devices and self.opts.mesh_devices > 1:
                # SPMD over the group axis: each chip advances its own
                # group rows; upload scatters, download gathers (the
                # "vote-matrix over ICI" configuration in BASELINE.md).
                # The whole compilation lives in parallel/mesh.py
                # (sharded_tick) — the engine consumes only the outputs
                # half of the (new_state, outputs) pair, so with
                # donate_state the input buffers are recycled into the
                # (discarded) new_state on device and nothing but the
                # [G] output rows crosses back to host.
                from tpuraft.parallel.mesh import (make_mesh, sharded_tick,
                                                   sharded_deadline_fold)

                n = self.opts.mesh_devices
                if self.G % n != 0:
                    raise ValueError(
                        f"max_groups={self.G} not divisible by "
                        f"mesh_devices={n}")
                mesh = make_mesh(n)  # raises if fewer devices exist
                full_tick = sharded_tick(
                    mesh, donate=self.opts.donate_state)
                self._tick_fn = lambda state, now, params: \
                    full_tick(state, now, params)[1]
                # earliest-deadline scan as one sharded fold + collective
                # min, instead of a host gather over every sharded row
                # per loop iteration
                self._deadline_fold = sharded_deadline_fold(mesh)
            else:
                # the PROCESS-WIDE jitted instance: all engines share one
                # trace cache, so only the first engine (per [G, P]
                # shape) pays a compile
                self._tick_fn = raft_tick_outputs_jit
            # warm the compile NOW, before any node registers: a first
            # tick mid-protocol would block the event loop for the
            # compile and miss every group's heartbeat window at once
            self.tick_once()
        if self.opts.profile_dir:
            if self._resolve_backend() == "numpy":
                LOG.warning("profile_dir set but backend is numpy: the "
                            "XLA profiler only traces the jax tick path")
            else:
                import jax

                try:
                    # process-global: a second engine in the same
                    # process cannot start another trace — it keeps
                    # running without one instead of failing startup
                    jax.profiler.start_trace(self.opts.profile_dir)
                    self._profiling = True
                except Exception as e:  # noqa: BLE001
                    LOG.warning("profiler trace not started (another "
                                "engine's trace active?): %s", e)
        from tpuraft.util import describer

        describer.register(self)
        self._task = asyncio.ensure_future(self._loop())

    async def shutdown(self) -> None:
        self._stopped = True
        from tpuraft.util import describer

        describer.unregister(self)
        if getattr(self, "_profiling", False):
            import jax

            self._profiling = False
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — trace already stopped
                LOG.warning("profiler stop: %s", e)
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _next_deadline(self) -> int:
        """Earliest engine-scheduled deadline (election, heartbeat or
        stepdown check) over controlled slots; a huge sentinel when
        none.  Quiescent slots schedule NOTHING — a fully hibernated
        engine sleeps until a dirty mark (wake, lease round, client
        traffic) arrives.  Mesh mode folds the scan on device (one
        sharded reduction + collective min) instead of gathering every
        sharded row back per loop iteration."""
        if self._deadline_fold is not None:
            from tpuraft.parallel.mesh import DEADLINE_NONE_I32

            nxt = int(self._deadline_fold(
                self.role, self.quiescent, self.has_ctrl,
                self.elect_deadline.astype(np.int32),
                self.hb_deadline.astype(np.int32),
                self.stepdown_deadline.astype(np.int32)))
            return (1 << 60) if nxt >= int(DEADLINE_NONE_I32) else nxt
        hc = self.has_ctrl & ~self.quiescent
        ec = hc & ((self.role == ROLE_FOLLOWER) | (self.role == ROLE_CANDIDATE))
        ld = hc & (self.role == ROLE_LEADER)
        nxt = 1 << 60
        if ec.any():
            nxt = min(nxt, int(self.elect_deadline[ec].min()))
        if ld.any():
            nxt = min(nxt, int(self.hb_deadline[ld].min()))
            nxt = min(nxt, int(self.stepdown_deadline[ld].min()))
        return nxt

    async def _loop(self) -> None:
        """Adaptive cadence: dirty -> tick now (sub-ms commit ack at low
        load); consecutive ticks pace by the previous tick's cost (a
        tunneled device batches more per dispatch); idle -> sleep to the
        next deadline, capped at tick_interval_ms."""
        max_idle_s = self.opts.tick_interval_ms / 1000.0
        min_pace_s = self.opts.min_tick_interval_ms / 1000.0
        while not self._stopped:
            now = self.now_ms()
            due = self._next_deadline() <= now
            if self._dirty or due:
                self._dirty_event.clear()
                self._dirty = False
                t0 = time.perf_counter()
                advanced = 0
                try:
                    advanced = self.tick_once()
                except Exception:
                    LOG.exception("engine tick failed")
                    self._dirty = True  # re-process pending acks next tick
                dur = time.perf_counter() - t0
                # measured tick dispatch cost: one input to the density-
                # aware election-timeout floor (_density_floor_ms)
                self._tick_cost_ema_s = (
                    dur if self._tick_cost_ema_s == 0.0
                    else 0.9 * self._tick_cost_ema_s + 0.1 * dur)
                pace = max(min_pace_s, dur * self.opts.pace_factor)
                if advanced == 0:
                    # a no-op tick (e.g. the leader's OWN ack before any
                    # follower responded) must not make the next real
                    # ack wait out the full pace window — that alone
                    # added ~1.5ms to the low-load commit-ack path.
                    # Debounce briefly (bounds tick spin under dirty
                    # storms), then let a dirty mark cut the remainder.
                    await asyncio.sleep(min(pace, 0.0003))
                    try:
                        await asyncio.wait_for(self._dirty_event.wait(),
                                               pace)
                    except asyncio.TimeoutError:
                        pass
                else:
                    await asyncio.sleep(pace)
                continue
            wait = min(max_idle_s,
                       max(0.0, (self._next_deadline() - now) / 1000.0))
            if self._dirty:
                continue
            try:
                await asyncio.wait_for(self._dirty_event.wait(), wait)
            except asyncio.TimeoutError:
                pass

    # -- the tick ------------------------------------------------------------

    def _rebase(self) -> None:
        hot = (self.match_abs.max(axis=1) - self.base) > _REBASE_LIMIT
        if hot.any():
            for s in np.nonzero(hot)[0]:
                new_base = self.commit_abs[s]
                self.pending_rel[s] = max(
                    1, self.pending_rel[s] - (new_base - self.base[s]))
                self.base[s] = new_base

    def tick_once(self) -> int:
        """One batched device tick for all groups: commit advancement,
        election/heartbeat scheduling, lease & step-down.  Returns the
        number of groups whose commit advanced."""
        t0 = time.perf_counter()
        now = self.now_ms()
        self._maybe_time_rebase(now)
        now = self.now_ms()
        self._rebase()
        # the leader's own slot counts as acked *now* (tick.py contract)
        lead_rows = np.nonzero((self.role == ROLE_LEADER)
                               & (self.self_col >= 0))[0]
        if lead_rows.size:
            self.last_ack[lead_rows, self.self_col[lead_rows]] = now
        rel = np.clip(self.match_abs - self.base[:, None], 0, None
                      ).astype(np.int32)
        commit_rel_now = np.clip(self.commit_abs - self.base, 0, None
                                 ).astype(np.int32)

        t1 = time.perf_counter()
        if self._tick_fn is not None:
            out = self._device_tick(rel, commit_rel_now, now)
        else:  # numpy fallback (tiny deployments / no jax)
            out = self._np_tick(rel, commit_rel_now, now)
        t2 = time.perf_counter()

        self.ticks += 1
        # publish the read-plane lane: the fused q_ack reduce is exactly
        # what per-read lease checks need, and the row it replaces is a
        # per-read [P] copy+sort on the hot GET path
        np.copyto(self.tick_q_ack, np.asarray(out.q_ack))
        advanced = self._apply_commits(out)
        self._apply_protocol(out, now)
        t3 = time.perf_counter()
        self.tick_hists["tick_build_ms"].update((t1 - t0) * 1e3)
        self.tick_hists["tick_device_ms"].update((t2 - t1) * 1e3)
        self.tick_hists["tick_apply_ms"].update((t3 - t2) * 1e3)
        self.tick_hists["tick_total_ms"].update((t3 - t0) * 1e3)
        if self._tick_tracer is not None:
            self._profile_tick(t0, t1, t2, t3, advanced)
        return advanced

    def _device_tick(self, rel, commit_rel_now, now):
        import jax

        from tpuraft.ops.tick import GroupState, TickParams

        if self._params_dev is None:
            self._params_dev = TickParams.make(self.eto_ms, self.hb_ms,
                                               self.lease_ms, self.snap_ms)
        # numpy mirrors go STRAIGHT into the jitted call — jit commits
        # them to the device itself, and an explicit jnp.asarray per
        # field doubles the per-tick host overhead (profiled: the
        # asarray+device_put pair dominated small-G tick cost)
        state = GroupState(
            role=self.role,
            commit_rel=commit_rel_now,
            pending_rel=self.pending_rel,
            match_rel=rel,
            granted=self.granted,
            voter_mask=self.voter_mask,
            old_voter_mask=self.old_voter_mask,
            elect_deadline=self.elect_deadline.astype(np.int32),
            hb_deadline=self.hb_deadline.astype(np.int32),
            last_ack=self.last_ack.astype(np.int32),
            snap_deadline=self.snap_deadline.astype(np.int32),
            quiescent=self.quiescent,
            witness_mask=self.witness_mask,
            stepdown_deadline=self.stepdown_deadline.astype(np.int32),
            fence_start=self.fence_start.astype(np.int32),
        )
        with jax.profiler.TraceAnnotation("tpuraft.raft_tick"):
            out = self._tick_fn(state, np.int32(now), self._params_dev)
        return jax.tree_util.tree_map(np.asarray, out)

    def _np_tick(self, rel, commit_rel_now, now) -> _NpOutputs:
        """Bit-exact numpy twin of tpuraft.ops.tick.raft_tick (the
        engine's no-jax fallback; also the oracle in engine tests)."""
        vm, ovm = self.voter_mask, self.old_voter_mask
        is_leader = self.role == ROLE_LEADER
        is_follower = self.role == ROLE_FOLLOWER
        is_candidate = self.role == ROLE_CANDIDATE

        q = _np_joint_quorum(rel, vm, ovm)
        if self._n_witness_slots:
            # witness commit clamp (ballot.witness_commit_clamp's numpy
            # twin): acked-by-witnesses-only indexes are not durable —
            # clamp to the best data-replica match.  Skipped entirely
            # while no registered conf carries witnesses (the steady
            # state for most engines).
            voters = vm | ovm
            wm = self.witness_mask
            has_w = (voters & wm).any(axis=1)
            data_best = np.where(voters & ~wm, rel, 0).max(axis=1)
            q = np.where(has_w, np.minimum(q, data_best), q).astype(np.int32)
        can_commit = is_leader & (q >= self.pending_rel)
        new_commit = np.where(can_commit, np.maximum(commit_rel_now, q),
                              commit_rel_now)

        def vote_ok(mask):
            n = mask.sum(axis=1)
            votes = (self.granted & mask).sum(axis=1)
            return (n > 0) & (votes >= n // 2 + 1)

        el = vote_ok(vm)
        in_joint = ovm.any(axis=1)
        if in_joint.any():
            elected_q = np.where(in_joint, el & vote_ok(ovm), el)
        else:
            elected_q = el  # steady state: no joint-config vote count
        # joint consensus: the lease needs BOTH configs responsive
        # (NodeImpl#checkDeadNodes walks conf and oldConf)
        ack64 = np.clip(self.last_ack, _NEG_I32, None).astype(np.int64)
        q_ack = _np_joint_order_stat(ack64, vm, ovm)
        have_ack = q_ack > _NEG_I32
        awake = ~self.quiescent
        return _NpOutputs(
            commit_rel=new_commit,
            commit_advanced=new_commit > commit_rel_now,
            elected=is_candidate & elected_q,
            election_due=(is_follower | is_candidate) & awake
            & (now >= self.elect_deadline),
            # step_down stays LIVE for quiescent leaders: store-lease
            # acks refresh their rows, so a dead store still deposes
            # its hibernating leaders (mirrors ops/tick.py)
            step_down=is_leader & have_ack & (now - q_ack >= self.eto_ms),
            hb_due=is_leader & awake & (now >= self.hb_deadline),
            lease_valid=is_leader & have_ack & (now - q_ack < self.lease_ms),
            snap_due=(self.role != ROLE_INACTIVE) & (self.snap_ms > 0)
            & (now >= self.snap_deadline),
            q_ack=q_ack,
            stepdown_due=is_leader & awake & (now >= self.stepdown_deadline),
            fence_ok=is_leader & (self.fence_start > _NEG_I32) & have_ack
            & (q_ack >= self.fence_start),
        )

    def eager_commit_slot(self, s: int) -> bool:
        """Event-driven commit advancement for ONE slot, on the ack path
        (TickOptions.eager_commit): the scalar mirror of the device
        tick's joint quorum reduce over this slot's [P] match row —
        joint-consensus aware (both quorums while ``old_voter_mask`` is
        populated), gated on the leadership window (``pending_rel``)
        exactly like ops/tick.py's ``can_commit``.  ~O(P log P) per
        ack on one row; the win is that a hot group's quorum closes on
        the ack that completes it instead of waiting out the tick
        pace.  The next tick recomputes the same value and finds
        nothing to advance (``commit_abs`` already moved)."""
        row = self.match_abs[s]

        def order_stat(mask: np.ndarray) -> int:
            vals = np.sort(row[mask])[::-1]
            n = vals.size
            return int(vals[n // 2]) if n else -1

        q = order_stat(self.voter_mask[s])
        if self.old_voter_mask[s].any():
            q = min(q, order_stat(self.old_voter_mask[s]))
        if self._n_witness_slots:
            # witness commit clamp, absolute-index domain (the scalar
            # mirror of the device tick's ballot.witness_commit_clamp)
            wm = self.witness_mask[s]
            voters = self.voter_mask[s] | self.old_voter_mask[s]
            if (voters & wm).any():
                data = voters & ~wm
                q = min(q, int(row[data].max()) if data.any() else 0)
        if q < self.base[s] + self.pending_rel[s] or q <= self.commit_abs[s]:
            return False
        self.commit_abs[s] = q
        self.eager_commits += 1
        box = self._boxes[s]
        if box is not None:
            box._advance(q)
        return True

    def _apply_commits(self, out) -> int:
        advanced = 0
        for s in np.nonzero(np.asarray(out.commit_advanced))[0]:
            box = self._boxes[s]
            if box is None:
                continue
            new_commit = int(self.base[s] + out.commit_rel[s])
            if new_commit > self.commit_abs[s]:
                self.commit_abs[s] = new_commit
                advanced += 1
                box._advance(new_commit)
        self.commit_advances += advanced
        return advanced

    def _apply_protocol(self, out, now: int) -> None:
        """Schedule slow-path handlers from the tick's event masks
        (controlled slots only); handlers re-verify under the node lock."""
        hc = self.has_ctrl
        for s in np.nonzero(np.asarray(out.election_due) & hc)[0]:
            ctrl = self._ctrls[s]
            if ctrl is None:
                continue
            # push the deadline NOW: the handler runs async, and a
            # same-deadline refire every tick until it runs would storm
            ctrl.push_election_deadline(now)
            ctrl.schedule("election_due", ctrl.node._on_election_due)
        for s in np.nonzero(np.asarray(out.elected) & hc)[0]:
            ctrl = self._ctrls[s]
            if ctrl is not None:
                ctrl.schedule("elected", ctrl.node._on_engine_elected)
        for s in np.nonzero(np.asarray(out.step_down) & hc)[0]:
            ctrl = self._ctrls[s]
            if ctrl is not None:
                ctrl.schedule("quorum_dead",
                              ctrl.node._on_engine_quorum_dead)
        for s in np.nonzero(np.asarray(out.stepdown_due) & hc)[0]:
            ctrl = self._ctrls[s]
            if ctrl is None:
                continue
            # re-arm the host mirror NOW (the handler runs async; a
            # same-deadline refire every tick would storm) on the
            # timer-mode cadence: eto/2, the reference stepDownTimer.
            self.stepdown_deadline[s] = now + max(1, int(self.eto_ms[s]) // 2)
            self.stepdown_ticks += 1
            # _check_dead_nodes re-verifies the quorum under the node
            # lock AND accrues priority_transfer_rounds — the exact
            # handler timer-mode runs, so decay-elected engine leaders
            # transfer back with zero node-side special casing
            ctrl.schedule("stepdown_tick", ctrl.node._check_dead_nodes)
        for s in np.nonzero(np.asarray(out.fence_ok) & hc)[0]:
            self._resolve_fences(int(s))
        hb_slots = np.nonzero(np.asarray(out.hb_due) & hc)[0]
        if hb_slots.size:
            self._flush_heartbeats(hb_slots, now)
        snap_slots = np.nonzero(np.asarray(out.snap_due) & hc)[0]
        for s in snap_slots:
            ctrl = self._ctrls[s]
            if ctrl is None:
                continue
            # advance the host mirror NOW (the handler runs async; a
            # same-deadline refire every tick would herd), keeping each
            # group on its own staggered phase
            self.snap_deadline[s] = now + int(self.snap_ms[s])
            ctrl.schedule("snapshot_due", ctrl.node._on_snapshot_due)

    def _flush_heartbeats(self, slots, now: int) -> None:
        """Batched heartbeat fan-out for all due leader groups: ONE
        HeartbeatHub.pulse per hub covering every due group this tick
        (the send-matrix plane — O(endpoints) RPCs, not O(groups))."""
        by_hub: dict[int, tuple[object, list]] = {}
        direct: list = []
        # phase-align each next beat to its group's hb_ms grid: groups
        # sharing an interval then fall due on the SAME tick, so one
        # pulse per interval carries every such group's beat (max hub
        # batching — staggered per-group beats degrade to ~1 per RPC).
        # Mirrors the device's deadline advance so masks don't refire.
        hbs = self.hb_ms[slots]
        self.hb_deadline[slots] = (now // hbs + 1) * hbs
        for s in slots:
            ctrl = self._ctrls[s]
            if ctrl is None:
                continue
            node = ctrl.node
            if not node.is_leader():
                continue
            # quiescence bookkeeping: count consecutive fully-acked idle
            # rounds; at the threshold the round's beats carry the
            # quiesce handshake (every follower must ack before the
            # group hibernates — see EngineControl.maybe_quiesce)
            ctrl.maybe_quiesce(now)
            if self.quiescent[s]:
                continue  # hibernated (e.g. single-voter: no handshake)
            reps = node.replicators.all()
            if not reps:
                continue
            nm = node.node_manager
            opt = node.options.raft_options.coalesce_heartbeats
            if nm is None or opt is False:
                direct.extend(reps)
                continue
            # AUTO (None): coalesce per peer once its responses advertise
            # multi_heartbeat — idle beats become O(endpoints) by default
            hub = nm.heartbeat_hub
            for r in reps:
                if opt is True or r.peer_multi_hb:
                    by_hub.setdefault(id(hub), (hub, []))[1].append(r)
                else:
                    direct.append(r)
        for hub, reps in by_hub.values():
            hub.pulse(reps)
        for r in direct:
            t = asyncio.ensure_future(r.send_heartbeat())
            t.add_done_callback(
                lambda tt: tt.cancelled() or tt.exception())


def _np_order_stat(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Per-row q-th largest among masked slots (q = n//2 + 1), NEG for
    empty masks — the numpy oracle of ops.ballot.quorum_match_index."""
    NEG = np.int64(_NEG_I32)
    v = np.where(mask, values, NEG)
    sd = -np.sort(-v, axis=1)
    n = mask.sum(axis=1)
    qi = np.clip(n // 2, 0, values.shape[1] - 1)
    picked = np.take_along_axis(sd, qi[:, None], axis=1)[:, 0]
    return np.where(n > 0, picked, NEG)


def _np_joint_order_stat(values: np.ndarray, vm: np.ndarray,
                         ovm: np.ndarray) -> np.ndarray:
    """Joint-consensus order statistic: min of both configs' q-th
    largest where a row is in joint mode — the shared shape of
    ballot.joint_quorum_match_index AND joint_quorum_ack_time."""
    new_q = _np_order_stat(values, vm)
    joint = ovm.any(axis=1)
    if not joint.any():
        # no group is mid membership-change (the steady state): skip
        # the old-config order statistic entirely — it is half the
        # tick's sort work (profiled: 4 sorts/tick -> 2)
        return new_q
    old_q = _np_order_stat(values, ovm)
    return np.where(joint, np.minimum(new_q, old_q), new_q)


def _np_joint_quorum(rel: np.ndarray, vm: np.ndarray, ovm: np.ndarray
                     ) -> np.ndarray:
    return _np_joint_order_stat(rel.astype(np.int64), vm, ovm
                                ).astype(np.int32)
