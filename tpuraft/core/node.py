"""Node: the per-group Raft state machine (host runtime).

Reference parity: ``core:core/NodeImpl`` (SURVEY.md §3.1 "Node lifecycle &
election", §4) — init/bootstrap, pre-vote + vote + become-leader/step-down,
apply pipeline, AppendEntries/RequestVote/TimeoutNow handlers, leader
lease + dead-quorum step-down, leadership transfer.  Membership change and
snapshotting hook in via ConfigurationCtx / SnapshotExecutor.

Concurrency model: everything runs on one asyncio loop; ``self._lock``
(FIFO asyncio.Lock) is the analog of NodeImpl's writeLock.  The lock is
held across follower-append fsync (durability ordering); the leader apply
path stages entries under the lock and fsyncs outside it.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
from typing import Awaitable, Callable, Optional

from tpuraft.conf import Configuration, ConfigurationEntry
from tpuraft.core.ballot_box import BallotBox
from tpuraft.core.fsm_caller import FSMCaller
from tpuraft.core.replicator import Replicator, ReplicatorGroup
from tpuraft.core.state_machine import StateMachine
from tpuraft.entity import (
    EMPTY_PEER,
    ElectionPriority,
    EntryType,
    LogEntry,
    LogId,
    PeerId,
    Task,
)
from tpuraft.errors import RaftError, RaftException, Status
from tpuraft.options import NodeOptions, ReadOnlyOption
from tpuraft.rpc.messages import (
    AppendEntriesRequest,
    AppendEntriesResponse,
    ReadIndexRequest,
    ReadIndexResponse,
    RequestVoteRequest,
    RequestVoteResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
)
from tpuraft.rpc.transport import RpcError
from tpuraft.util import clock as clockmod
from tpuraft.util import describer
from tpuraft.util.trace import (RECORDER, TRACER, adopt_entry_ctx,
                                store_proc)
from tpuraft.storage.log_manager import LogManager
from tpuraft.storage.log_storage import create_log_storage
from tpuraft.storage.meta_storage import MemoryRaftMetaStorage, RaftMetaStorage
from tpuraft.util.metrics import MetricRegistry
from tpuraft.util.timer import RepeatedTimer

LOG = logging.getLogger(__name__)


class State(enum.Enum):
    UNINITIALIZED = "uninitialized"
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"
    TRANSFERRING = "transferring"
    ERROR = "error"
    SHUTTING = "shutting"
    SHUTDOWN = "shutdown"


class _VoteCtx:
    """Vote tally for one (pre-)vote round — scalar mirror of
    ops.ballot.joint_vote_quorum."""

    def __init__(self, conf: Configuration, old_conf: Configuration):
        self.peers = set(conf.peers)
        self.old_peers = set(old_conf.peers)
        self.granted: set[PeerId] = set()

    def grant(self, peer: PeerId) -> None:
        self.granted.add(peer)

    def is_granted(self) -> bool:
        new_ok = len(self.granted & self.peers) >= len(self.peers) // 2 + 1
        if not self.old_peers:
            return new_ok
        old_ok = len(self.granted & self.old_peers) >= len(self.old_peers) // 2 + 1
        return new_ok and old_ok


# graftcheck: loop-confined
class TimerControl:
    """Reference-parity control plane: per-group RepeatedTimers + scalar
    tallies (``NodeImpl``'s electionTimer / voteTimer / stepDownTimer and
    the Replicator lastRpcSendTimestamp map behind ``checkDeadNodes``).

    Engine-backed nodes swap this for ``tpuraft.core.engine.
    EngineControl`` (via ``TpuBallotBox.make_control``): the same call
    surface, but deadlines/acks/votes live in the engine's ``[G, P]``
    mirrors and fire from the fused device tick's masks instead of
    O(groups) asyncio timers — the SURVEY §8.1 device plane.
    """

    drives_heartbeats = False   # per-replicator loops / hub clock beat

    def __init__(self, node: "Node"):
        self._node = node
        opts = node.options
        self._clock = clockmod.resolve(opts.clock)
        self._acks: dict[PeerId, float] = {}
        self._vote_ctx: Optional[_VoteCtx] = None
        self._election_timer = RepeatedTimer(
            f"election-{node.server_id}", opts.election_timeout_ms,
            node._handle_election_timeout, adjust=RepeatedTimer.random_adjust,
            clock=opts.clock)
        self._vote_timer = RepeatedTimer(
            f"vote-{node.server_id}", opts.election_timeout_ms,
            node._handle_vote_timeout, adjust=RepeatedTimer.random_adjust,
            clock=opts.clock)
        self._stepdown_timer = RepeatedTimer(
            f"stepdown-{node.server_id}", opts.election_timeout_ms // 2 or 1,
            node._check_dead_nodes, clock=opts.clock)

    # -- role transitions ----------------------------------------------------

    def start_follower(self) -> None:
        self._election_timer.start()

    def note_leader_contact(self) -> None:
        pass  # the election handler's lease check covers timer mode

    def note_activity(self) -> None:
        pass  # timer-mode nodes never quiesce (EngineControl wakes)

    def on_candidate(self) -> None:
        self._election_timer.stop()
        self._vote_timer.start()

    def stop_vote_wait(self) -> None:
        self._vote_timer.stop()

    def on_leader(self) -> None:
        self._vote_timer.stop()
        self._acks = {self._node.server_id: self._clock.monotonic()}
        self._stepdown_timer.start()

    def on_step_down(self, was_candidate: bool, was_leader: bool) -> None:
        if was_candidate:
            self._vote_timer.stop()
        if was_leader:
            self._stepdown_timer.stop()
        self._vote_ctx = None

    def on_follower(self) -> None:
        self._election_timer.restart()

    # -- vote tally ----------------------------------------------------------

    def start_vote_round(self) -> bool:
        """Open a vote round granted by self; True = already a quorum."""
        node = self._node
        ctx = _VoteCtx(node.conf_entry.conf, node.conf_entry.old_conf)
        ctx.grant(node.server_id)
        self._vote_ctx = ctx
        return ctx.is_granted()

    def grant_vote(self, peer: PeerId) -> bool:
        ctx = self._vote_ctx
        if ctx is None:
            return False
        ctx.grant(peer)
        return ctx.is_granted()

    # -- ack bookkeeping (leader lease / dead-quorum / alive peers) ----------

    def record_ack(self, peer: PeerId, when: float) -> None:
        if when > self._acks.get(peer, 0.0):
            self._acks[peer] = when

    def quorum_ack_age_s(self) -> float:
        """Age of the q-th newest voter ack (joint-consensus aware);
        self counts as acked now (NodeImpl#checkDeadNodes)."""
        node = self._node
        now = self._clock.monotonic()
        self._acks[node.server_id] = now
        conf, old_conf = node.conf_entry.conf, node.conf_entry.old_conf

        def q_ack(peers: list[PeerId]) -> float:
            acks = sorted((self._acks.get(p, 0.0) for p in peers),
                          reverse=True)
            return acks[len(peers) // 2] if peers else 0.0

        qa = q_ack(conf.peers)
        if not old_conf.is_empty():
            qa = min(qa, q_ack(old_conf.peers))
        return now - qa

    def lease_valid(self) -> bool:
        node = self._node
        ro = node.options.raft_options
        lease_s = (node.options.election_timeout_ms
                   * ro.leader_lease_time_ratio / 1000.0)
        # drift bound (ISSUE 18): the holder trusts its lease for
        # (1 - rho) of the granted window so a clock running up to rho
        # slow can never stretch the real window past the grant
        lease_s *= (1.0 - ro.clock_drift_bound)
        sentinel = node.options.clock_sentinel
        if sentinel is not None and not sentinel.lease_check():
            # the local clock is drift-suspect beyond rho: the bound's
            # premise is broken — fail closed (reads take SAFE)
            return False
        return self.quorum_ack_age_s() < lease_s

    def alive_peers(self) -> list[PeerId]:
        node = self._node
        horizon = (self._clock.monotonic()
                   - node.options.election_timeout_ms / 1000.0)
        return [p for p in node.list_peers()
                if p == node.server_id or self._acks.get(p, 0.0) > horizon]

    # -- lifecycle -----------------------------------------------------------

    def deactivate(self) -> None:
        self._stop_timers()

    def shutdown(self) -> None:
        self._stop_timers()

    def _stop_timers(self) -> None:
        for t in (self._election_timer, self._vote_timer,
                  self._stepdown_timer):
            t.stop()


class Node:
    def __init__(self, group_id: str, server_id: PeerId, options: NodeOptions,
                 transport, ballot_box_factory=None):
        self.group_id = group_id
        self.server_id = server_id
        self.options = options
        self.transport = transport
        # SPI seam (reference: DefaultJRaftServiceFactory / JRaftServiceLoader):
        # the MultiRaftEngine plugs TpuBallotBox in here; everything else in
        # the node is untouched by the device plane
        self._ballot_box_factory = ballot_box_factory or BallotBox
        self.metrics = MetricRegistry(options.enable_metrics)
        # injectable time plane (ISSUE 18): ONE store-level clock feeds
        # every lease/timer comparison this node makes; SYSTEM when none
        self._clock = clockmod.resolve(options.clock)

        # Protocol state below is guarded-by the node lock in WRITE mode
        # (graftcheck guarded-by): every rebind happens under
        # ``async with self._lock`` (or in a helper annotated
        # ``holds(_lock)``); single reads on the owning event loop are
        # safe without it — the lock serializes multi-await critical
        # sections, not loop-atomic reads.
        self.state = State.UNINITIALIZED        # guarded-by: _lock (writes)
        self.current_term = 0                   # guarded-by: _lock (writes)
        self.leader_id: PeerId = EMPTY_PEER     # guarded-by: _lock (writes)
        self.voted_for: PeerId = EMPTY_PEER     # guarded-by: _lock (writes)
        self.conf_entry = ConfigurationEntry()  # guarded-by: _lock (writes)

        self.log_manager: LogManager = None  # type: ignore[assignment]
        self.fsm_caller: FSMCaller = None  # type: ignore[assignment]
        self.ballot_box: BallotBox = None  # type: ignore[assignment]
        self.replicators = ReplicatorGroup(self)
        self.snapshot_executor = None  # set in init when snapshot_uri given
        self.read_only_service = None
        self.node_manager = None  # set by RaftGroupService (file service)
        # store-wide write plane (AppendBatcher): when the hosting store
        # attaches one, this node's replicators submit their windows to
        # it instead of the per-endpoint send-plane lane — one windowed
        # store_append round per destination carries every led group's
        # pending entries (the read plane's ReadConfirmBatcher mirror)
        self.append_batcher = None

        self._meta: RaftMetaStorage = None  # type: ignore[assignment]
        self._lock = asyncio.Lock()
        # control plane: TimerControl (per-group timers, reference
        # parity) or EngineControl (device-tick masks) — set in init()
        self._ctrl = None
        self._note_append_start = None  # replica-plane hooks (init())
        self._note_attested = None
        self._snapshot_timer: Optional[RepeatedTimer] = None
        self._last_leader_timestamp = self._clock.monotonic()  # guarded-by: _lock (writes)
        # index of the first entry appended in THIS leadership term (the
        # election no-op); reads are unsafe until it commits
        self._term_first_index: int = 0         # guarded-by: _lock (writes)
        self._conf_ctx: Optional["_ConfigurationCtx"] = None  # guarded-by: _lock (writes)
        # chaos-harness hook: called as listener(node, stage) on every
        # _ConfigurationCtx stage transition (catching_up/joint/stable/
        # aborted) — lets a nemesis land a seeded crash mid-stage
        self.conf_stage_listener: Optional[Callable[["Node", str], None]] = None
        self._transfer_deadline: float = 0.0    # guarded-by: _lock (writes)
        self._shutdown_event = asyncio.Event()
        self._wakeup_candidate: Optional[PeerId] = None
        # priority election [1.3+] (reference: NodeImpl targetPriority /
        # electionTimeoutCounter): a node whose priority is below the
        # current target skips election rounds; the target decays after
        # repeated skipped rounds so the group still converges when all
        # high-priority nodes are dead
        self.target_priority: int = ElectionPriority.DISABLED  # guarded-by: _lock (writes)
        self._election_round: int = 0           # guarded-by: _lock (writes)
        # priority RE-election (geo): consecutive stepdown-timer rounds a
        # healthy higher-priority voter has been caught up and acking
        self._priority_transfer_rounds: int = 0  # guarded-by: _lock (writes)
        # gray failures: election rounds this node skipped because its
        # own store scored SICK (options.health) — a slow store should
        # not WIN elections, but liveness demands it may still campaign
        # once every healthy peer had its chance
        self._sick_election_skips: int = 0      # guarded-by: _lock (writes)
        # trace plane: staged index -> (trace context, stage perf_counter)
        # for traced entries awaiting their quorum — _on_committed pops
        # and emits the quorum_commit span; only sampled/staged ops ever
        # enter, so the steady-state cost is one empty-dict branch
        self._trace_quorum: dict[int, tuple[int, float]] = {}
        self._trace_proc = store_proc(server_id)

    # ======================================================================
    # lifecycle
    # ======================================================================

    # graftcheck: allow(guarded-by) — init-time: completes before any RPC handler or timer can race it
    async def init(self) -> bool:
        opts = self.options
        if opts.initial_conf.is_witness(self.server_id):
            # the operator's conf string flags THIS node '/witness'
            # (e.g. --peers a,b,c/witness on a bare server): adopt the
            # role without a separate flag — the conf is the truth
            opts.witness = True
        if opts.witness:
            # a witness journals metadata only: whatever FSM the hosting
            # engine wired (a KV store's) must never see the payload-
            # stripped entries — shadow it with the null witness FSM
            from tpuraft.core.state_machine import WitnessStateMachine

            opts.fsm = WitnessStateMachine()
        # meta
        if opts.raft_meta_uri.startswith("file://"):
            self._meta = RaftMetaStorage(opts.raft_meta_uri[len("file://"):],
                                         sync=opts.raft_options.sync_meta)
        elif opts.raft_meta_uri.startswith("multimeta://"):
            # shared fsynced meta journal: multimeta://<dir>#<group> —
            # every group of the process joins one group-commit round,
            # so an election herd's {term, votedFor} persists cost one
            # fsync, not G (storage/meta_multilog.py)
            rest = opts.raft_meta_uri[len("multimeta://"):]
            if "#" not in rest:
                raise ValueError(
                    "multimeta:// needs a group fragment: "
                    "multimeta://<dir>#<group>")
            mdir, mgroup = rest.rsplit("#", 1)
            from tpuraft.storage.meta_multilog import MultiRaftMetaStorage

            self._meta = MultiRaftMetaStorage(mdir, mgroup)
        elif opts.raft_meta_uri in ("", "memory://"):
            self._meta = MemoryRaftMetaStorage()
        else:
            # NO silent fallthrough to volatile meta: a typo'd scheme
            # silently dropping {term, votedFor} durability is a
            # double-vote hazard, not a default
            raise ValueError(
                f"unknown raft_meta_uri scheme: {opts.raft_meta_uri!r} "
                "(expected file://, multimeta://, memory:// or empty)")
        self._meta.init()
        self.current_term = self._meta.term
        self.voted_for = self._meta.voted_for

        # log
        storage = create_log_storage(opts.log_uri)
        self.log_manager = LogManager(
            storage,
            sync=opts.raft_options.sync,
            max_flush_batch=opts.raft_options.max_entries_size,
            max_logs_in_memory=opts.raft_options.max_logs_in_memory,
            max_logs_in_memory_bytes=(
                opts.raft_options.max_logs_in_memory_bytes),
            health=opts.health,
            trace_proc=self._trace_proc,
            disk_budget=opts.disk_budget,
        )
        await self.log_manager.init()
        # storage-flush failure (ENOSPC, EIO) -> leader step-down with
        # retryable client errors, never process death (ISSUE 17 layer 4)
        self.log_manager.on_storage_error = self._on_log_storage_error

        # fsm pipeline
        self.ballot_box = self._ballot_box_factory(self._on_committed)
        # replica-plane boxes tap the log's durable-advance stream (their
        # row of the [R, G] collective commit plane IS this node's
        # stable index — no ack echo needed for co-located replicas) and
        # the attestation hooks that term-scope the row (plane SAFETY)
        attach = getattr(self.ballot_box, "attach_log_manager", None)
        if attach is not None:
            attach(self.log_manager)
        self._note_append_start = getattr(
            self.ballot_box, "note_append_start", None)
        self._note_attested = getattr(self.ballot_box, "note_attested", None)
        self.fsm_caller = FSMCaller(
            opts.fsm, self.log_manager,
            apply_batch=opts.raft_options.apply_batch,
            on_error=self._on_fsm_error,
            health=opts.health,
            trace_proc=self._trace_proc,
            apply_lane=opts.apply_lane)
        self.fsm_caller.on_configuration_applied = self._on_configuration_applied

        # snapshot subsystem
        bootstrap = LogId(0, 0)
        if opts.snapshot_uri:
            from tpuraft.core.snapshot_executor import SnapshotExecutor

            self.snapshot_executor = SnapshotExecutor(self, opts.snapshot_uri)
            bootstrap = await self.snapshot_executor.init()
        await self.fsm_caller.init(bootstrap)
        if bootstrap.index > 0:
            self.ballot_box.last_committed_index = bootstrap.index

        # configuration: snapshot conf > log conf > initial conf
        last_conf = self.log_manager.conf_manager.last()
        if not last_conf.conf.is_empty():
            self.conf_entry = last_conf
        else:
            self.conf_entry = ConfigurationEntry(
                LogId(0, 0), opts.initial_conf.copy())

        if not opts.witness and (
                self.conf_entry.conf.is_witness(self.server_id)
                or self.conf_entry.old_conf.is_witness(self.server_id)):
            # restart of a runtime-adopted witness whose operator did
            # not pass the boot flag: the LOG's conf is the truth
            self._adopt_witness_mode()
        self.ballot_box.update_conf(self.conf_entry.conf,
                                    self.conf_entry.old_conf)
        self._refresh_target_priority()

        st = self.log_manager.check_consistency()
        if not st.is_ok():
            LOG.error("%s: log inconsistent: %s", self, st)
            return False

        from tpuraft.core.read_only import ReadOnlyService

        self.read_only_service = ReadOnlyService(self)

        # control plane: the engine's ballot box hands out an
        # EngineControl (device-tick deadlines/votes/acks); every other
        # box type falls back to per-group timers
        make_ctrl = getattr(self.ballot_box, "make_control", None)
        self._ctrl = make_ctrl(self) if make_ctrl is not None else None
        if self._ctrl is None:
            self._ctrl = TimerControl(self)
        if self.snapshot_executor and opts.snapshot.interval_secs > 0 \
                and not getattr(self._ctrl, "drives_snapshots", False):
            # host timer only for timer-mode nodes: engine-backed nodes
            # get their cadence from the device tick's snap_due mask
            # (one [G] deadline row, jitter-staggered — no per-group
            # RepeatedTimer, no unstaggered snapshot herd at high G)
            self._snapshot_timer = RepeatedTimer(
                f"snapshot-{self.server_id}", opts.snapshot.interval_secs * 1000,
                self._handle_snapshot_timeout, clock=opts.clock)
            self._snapshot_timer.start()

        self.state = State.FOLLOWER
        self._last_leader_timestamp = self._clock.monotonic()
        self._ctrl.start_follower()
        LOG.info("%s initialized: term=%d conf=%s", self, self.current_term,
                 self.conf_entry.conf)

        describer.register(self)

        # single-voter group elects itself immediately (a witness never
        # self-elects — it never campaigns at all)
        if (self.conf_entry.conf.peers == [self.server_id]
                and self.conf_entry.old_conf.is_empty()
                and not opts.witness):
            async with self._lock:
                await self._elect_self()
        return True

    async def shutdown(self) -> None:
        async with self._lock:
            if self.state in (State.SHUTTING, State.SHUTDOWN):
                return
            prev_state = self.state
            self.state = State.SHUTTING
            if self._conf_ctx is not None:
                # an in-flight membership change must not wedge its
                # waiter (the admin RPC / nemesis driver) forever
                self._conf_ctx.fail(Status.error(
                    RaftError.ENODESHUTTING, "node is shutting down"))
                self._conf_ctx = None
            if self._ctrl is not None:
                self._ctrl.shutdown()
            if self._snapshot_timer:
                self._snapshot_timer.stop()
            self.replicators.stop_all()
            if prev_state in (State.LEADER, State.TRANSFERRING):
                self.fsm_caller.fail_pending_closures(
                    Status.error(RaftError.ENODESHUTTING, "node is shutting down"))
        if self.read_only_service:
            await self.read_only_service.shutdown()
        if self.snapshot_executor:
            await self.snapshot_executor.shutdown()
        await self.fsm_caller.shutdown()
        await self.log_manager.shutdown()
        self.ballot_box.close()
        self._meta.shutdown()
        describer.unregister(self)
        # SHUTTING (set under the lock above) already refuses every other
        # writer, and a shutdown must never queue behind a straggler
        # holding the lock (a wedged holder would wedge join() with it)
        self.state = State.SHUTDOWN  # graftcheck: allow(guarded-by) — terminal write; SHUTTING already excludes all other writers
        self._shutdown_event.set()

    async def join(self) -> None:
        """Block until shutdown completes (reference: Node#join)."""
        await self._shutdown_event.wait()

    # ======================================================================
    # public API (reference: Node interface — SURVEY.md §9)
    # ======================================================================

    def is_leader(self) -> bool:
        return self.state in (State.LEADER, State.TRANSFERRING)

    def get_leader_id(self) -> PeerId:
        return self.leader_id

    def describe(self) -> str:
        """Live-state text dump (reference [1.3+]: NodeImpl#describe)."""
        lm = self.log_manager
        lines = [
            f"{self}:",
            f"  state: {self.state.value}  term: {self.current_term}"
            f"  leader: {self.leader_id}",
            f"  conf: {self.conf_entry.conf}"
            + (f"  old_conf: {self.conf_entry.old_conf}"
               if not self.conf_entry.old_conf.is_empty() else ""),
            f"  log: [{lm.first_log_index()}, {lm.last_log_index()}]"
            f"  snapshot: {lm.last_snapshot_id()}",
            f"  commit: {self.ballot_box.last_committed_index}"
            f"  applied: {self.fsm_caller.last_applied_index}"
            f"  pending: {self.ballot_box.pending_index}",
            f"  target_priority: {self.target_priority}"
            + ("  witness: true" if self.options.witness else ""),
        ]
        rows = self.replicators.progress()
        if rows:
            lines.append("  replicators:")
            for peer, next_index, matched in rows:
                lines.append(
                    f"    {peer}: next={next_index} matched={matched}")
        if self.metrics.counters:
            lines.append(f"  counters: {dict(self.metrics.counters)}")
        return "\n".join(lines)

    def list_peers(self) -> list[PeerId]:
        return list(self.conf_entry.conf.peers)

    def list_learners(self) -> list[PeerId]:
        return list(self.conf_entry.conf.learners)

    async def apply(self, task: Task) -> None:
        """Replicate task.data; task.done(status) fires on commit/failure."""
        await self.apply_batch([task])

    async def apply_batch(self, tasks: list[Task]) -> None:
        """Stage a BATCH of tasks as consecutive log entries under ONE
        lock acquisition / flush wait (reference:
        ``NodeImpl#executeApplyingTasks`` — the apply Disruptor drains up
        to ``applyBatch=32`` tasks per event).  Each task still becomes
        its own entry with its own completion closure."""
        if not tasks:
            return
        async with self._lock:
            if self.state != State.LEADER:
                st = (Status.error(RaftError.EBUSY, "leadership transferring")
                      if self.state == State.TRANSFERRING
                      else Status.error(RaftError.EPERM,
                                        f"not leader (state={self.state.value})"))
                for task in tasks:
                    if task.done:
                        task.done(st)
                return
            good: list[Task] = []
            for task in tasks:
                if task.expected_term not in (-1, self.current_term):
                    if task.done:
                        task.done(Status.error(
                            RaftError.EPERM,
                            f"expected term {task.expected_term} != "
                            f"{self.current_term}"))
                    continue
                good.append(task)
            if not good:
                return
            entries = [LogEntry(type=EntryType.DATA, data=t.data,
                                trace_id=t.trace_id)
                       for t in good]
            self._ctrl.note_activity()  # a write instantly wakes a
            # hibernating leader group (quiescence)
            term = self.current_term
            last_id = self.log_manager.stage_leader_entries(entries, term)
            first_index = last_id.index - len(good) + 1
            if TRACER.enabled:
                now = time.perf_counter()
                for i, task in enumerate(good):
                    if task.trace_id:
                        self._trace_quorum[first_index + i] = (
                            task.trace_id, now)
            for i, task in enumerate(good):
                if task.done:
                    self.fsm_caller.append_pending_closure(
                        first_index + i, task.done,
                        ack_at_commit=task.ack_at_commit)
            self.replicators.wake_all()
        # fsync outside the lock; batched with concurrent appliers
        try:
            await self.log_manager.flush_staged(last_id.index)
        except RaftException:
            # flush failed (ENOSPC/EIO): the flush loop's
            # on_storage_error hook steps this leader down, failing the
            # pending closures with retryable ENEWLEADER — nothing here
            # may count toward commit
            return
        async with self._lock:
            if self.state in (State.LEADER, State.TRANSFERRING) \
                    and self.current_term == term:
                self._commit_at_self(last_id.index)

    def _commit_at_self(self, index: int) -> None:  # graftcheck: holds(_lock)
        self.ballot_box.commit_at(
            self.server_id, index, self.conf_entry.conf, self.conf_entry.old_conf)

    async def snapshot(self) -> Status:
        if not self.snapshot_executor:
            return Status.error(RaftError.EINVAL, "snapshot storage not configured")
        return await self.snapshot_executor.do_snapshot()

    async def read_index(self) -> int:
        """Linearizable read barrier: resolves to a safe read index once
        the local FSM has applied up to it (reference: Node#readIndex)."""
        return await self.read_only_service.read_index()

    def read_committed_user_log(self, index: int) -> LogEntry:
        """Fetch the first committed DATA entry at or after ``index``
        from the local log (reference: NodeImpl#readCommittedUserLog —
        same forward-skip over NO_OP/CONFIGURATION entries).  Raises
        RaftException: EINVAL for an index beyond the commit point,
        ENOENT when the range was compacted away or holds no user log.
        """
        committed = self.ballot_box.last_committed_index
        if index <= 0 or index > committed:
            raise RaftException(Status.error(
                RaftError.EINVAL,
                f"index {index} out of committed range [1, {committed}]"))
        first = self.log_manager.first_log_index()
        if index < first:
            raise RaftException(Status.error(
                RaftError.ENOENT,
                f"log at {index} compacted (first index {first})"))
        for i in range(index, committed + 1):
            entry = self.log_manager.get_entry(i)
            if entry is None:  # compacted under us
                raise RaftException(Status.error(
                    RaftError.ENOENT, f"log at {i} compacted concurrently"))
            if entry.type == EntryType.DATA:
                return entry
        raise RaftException(Status.error(
            RaftError.ENOENT,
            f"no user log in committed range [{index}, {committed}]"))

    async def transfer_leadership_to(self, peer: PeerId) -> Status:
        async with self._lock:
            if self.state != State.LEADER:
                return Status.error(RaftError.EPERM, "not leader")
            if peer == self.server_id:
                return Status.OK()  # already the leader
            if self._conf_ctx is not None:
                # a transfer mid-change would hand the (possibly joint)
                # conf to a leader with no ctx driving it to completion;
                # the change resumes it, but racing the two on purpose is
                # an operator error (reference: NodeImpl refuses too)
                return Status.error(RaftError.EBUSY,
                                    "membership change in progress")
            if not self.conf_entry.conf.contains(peer):
                return Status.error(RaftError.EINVAL, f"{peer} not in conf")
            if self.conf_entry.conf.is_witness(peer):
                # a witness can never lead (metadata-only journal, null
                # FSM) — refusing here keeps TimeoutNow from ever being
                # aimed at one
                return Status.error(
                    RaftError.EINVAL, f"{peer} is a witness (cannot lead)")
            r = self.replicators.get(peer)
            if r is None:
                return Status.error(RaftError.EINVAL, f"no replicator for {peer}")
            self.state = State.TRANSFERRING
            self._transfer_deadline = (
                self._clock.monotonic()
                + self.options.election_timeout_ms / 1000.0)
            r.transfer_leadership(self.log_manager.last_log_index())
            r.wake()
            LOG.info("%s transferring leadership to %s", self, peer)
            asyncio.ensure_future(
                self._transfer_watchdog(peer, self.current_term))
            return Status.OK()

    async def _transfer_watchdog(self, peer: PeerId, term: int) -> None:
        await asyncio.sleep(self.options.election_timeout_ms / 1000.0)
        async with self._lock:
            # the term pins the watchdog to ITS transfer: deposed and
            # re-elected within the sleep, a new transfer may be in
            # flight — a stale watchdog resuming LEADER for it would arm
            # change_peers while the new target's TimeoutNow is pending
            if self.state == State.TRANSFERRING and self.current_term == term:
                LOG.info("%s leadership transfer timed out; resuming", self)
                self.state = State.LEADER
                # cancel the pending TimeoutNow trigger: the target
                # catching up later must not depose the resumed leader
                r = self.replicators.get(peer)
                if r is not None:
                    r.stop_transfer_leadership()

    # ======================================================================
    # apply-side commit plumbing
    # ======================================================================

    def _on_committed(self, index: int) -> None:
        if self._trace_quorum:
            now = time.perf_counter()
            for idx in [i for i in self._trace_quorum if i <= index]:
                tid, t0 = self._trace_quorum.pop(idx)
                TRACER.span(tid, "quorum_commit", t0, now,
                            proc=self._trace_proc, index=idx)
        self.fsm_caller.on_committed(index)
        self.metrics.counter("commits", 1)

    def on_match_advanced(self, peer: PeerId, match_index: int) -> None:
        if not self.is_leader():
            return
        e = self.conf_entry
        if not (e.contains(peer) or peer in e.conf.learners
                or peer in e.old_conf.learners):
            # a RETIRING replicator (removed peer still being shipped its
            # removal entry) must not repopulate the ballot row that
            # update_conf just pruned — a later wipe+re-add of the same
            # peer would inherit the stale row and commit on a phantom ack
            return
        self.ballot_box.commit_at(peer, match_index, e.conf, e.old_conf)

    def on_peer_ack(self, peer: PeerId, when: float) -> None:
        self._ctrl.record_ack(peer, when)

    def list_alive_peers(self) -> list[PeerId]:
        """Peers heard from within one election timeout (leader only;
        reference: CliServiceImpl#getAlivePeers via Replicator lastRpcSendTimestamp)."""
        return self._ctrl.alive_peers()

    # ======================================================================
    # election machinery
    # ======================================================================

    def _leader_lease_valid(self) -> bool:
        if (self._clock.monotonic() - self._last_leader_timestamp
                < self.options.election_timeout_ms
                * self.options.raft_options.leader_lease_time_ratio / 1000.0):
            return True
        # quiescent follower: the per-group leader-contact timestamp
        # legitimately goes stale (beats are suppressed) — 'my leader is
        # alive' is delegated to its STORE's liveness lease, so the vote
        # guards and the election-timeout lease check stay closed exactly
        # as long as the store lease flows (hibernate-raft safety)
        q = getattr(self._ctrl, "quiescent_leader_alive", None)
        return q is not None and q()

    def _believes_leader_alive(self) -> bool:
        """Is there, from THIS node's view, a live leader right now?  On
        a follower that is the leader-contact lease; on the leader
        itself it is its own quorum-ack lease (the follower-side
        timestamp is not refreshed while leading)."""
        if self.is_leader():
            return self._ctrl.lease_valid()
        return not self.leader_id.is_empty() and self._leader_lease_valid()

    # -- priority election [1.3+] ------------------------------------------

    def _refresh_target_priority(self) -> None:  # graftcheck: holds(_lock)
        """Target = max priority among current DATA voters (incl. self).
        Reference: NodeImpl#getMaxPriorityOfNodes on conf / leader change.
        Witness voters are excluded: they never campaign, so their
        priority raising the bar would only delay real candidates."""
        witnesses = set(self.conf_entry.conf.witnesses) \
            | set(self.conf_entry.old_conf.witnesses)
        prios = [p.priority for p in
                 (set(self.conf_entry.conf.peers)
                  | set(self.conf_entry.old_conf.peers)
                  | {self.server_id}) - witnesses]
        self.target_priority = max(prios) if prios else ElectionPriority.DISABLED
        self._election_round = 0

    def _allow_launch_election(self) -> bool:  # graftcheck: holds(_lock)
        """Gate an election round by priority (reference:
        NodeImpl#allowLaunchElection).  Caller holds the lock."""
        if self.options.witness:
            # a witness NEVER campaigns (the NOT_ELECTED contract): it
            # holds no payloads, so leading would serve reads/commits
            # from a metadata-only journal.  Witness-majority partitions
            # therefore can never elect, hence never commit — the
            # witness-safety property tests/test_witness.py proves.
            return False
        from tpuraft.util.health import SICK

        health = self.options.health
        if (health is not None and self.options.sick_election_rounds > 0
                and health.score() == SICK):
            # gray-failure election gate: a SICK store skips rounds so
            # a healthy peer wins instead — but only boundedly, or a
            # cluster whose every store is slow could never elect.
            # Mirrors the priority-decay shape below: defer, then
            # concede to liveness.
            self._sick_election_skips += 1
            if self._sick_election_skips <= self.options.sick_election_rounds:
                LOG.info("%s deferring election: local store is SICK "
                         "(round %d/%d)", self, self._sick_election_skips,
                         self.options.sick_election_rounds)
                return False
        else:
            self._sick_election_skips = 0
        prio = self.server_id.priority
        if prio == ElectionPriority.DISABLED:
            return True
        if prio == ElectionPriority.NOT_ELECTED:
            LOG.debug("%s priority NOT_ELECTED: never starts elections", self)
            return False
        if prio >= self.target_priority:
            self._election_round = 0
            return True
        self._election_round += 1
        if self._election_round > 1:
            # nobody higher won in time: decay the bar so the group
            # still converges with all high-priority nodes dead
            gap = max(self.options.raft_options.decay_priority_gap,
                      self.target_priority // 5)
            self.target_priority = max(ElectionPriority.MIN_VALUE,
                                       self.target_priority - gap)
            self._election_round = 0
            LOG.info("%s decayed target priority to %d", self,
                     self.target_priority)
            if prio >= self.target_priority:
                return True  # elect this round, not an extra timeout later
        return False

    async def _handle_election_timeout(self) -> None:
        async with self._lock:
            if self.state != State.FOLLOWER:
                return
            if not self.conf_entry.contains(self.server_id):
                return  # not a participant (e.g. learner or removed)
            if self._leader_lease_valid():
                return
            if not self._allow_launch_election():
                return
            prev_leader = self.leader_id
            self.leader_id = EMPTY_PEER
            if not prev_leader.is_empty():
                self.fsm_caller.on_stop_following(prev_leader, self.current_term)
            await self._pre_vote()

    async def _persist_meta(self, term: int, voted_for: PeerId) -> None:
        """Durably record {term, votedFor}.  File-backed meta fsyncs in
        an executor thread; volatile meta (memory://) writes two fields
        — the executor hop for it was pure overhead, and at high group
        counts an election herd paid tens of thousands of pointless
        thread round-trips."""
        if getattr(self._meta, "SYNC_CHEAP", False):
            self._meta.set_term_and_voted_for(term, voted_for)
            return
        save_async = getattr(self._meta, "save_async", None)
        if save_async is not None:
            # shared meta journal: stage inline, join the engine-wide
            # group-commit — concurrent groups' meta fsyncs coalesce
            await save_async(term, voted_for)
            return
        await asyncio.get_running_loop().run_in_executor(
            None, self._meta.set_term_and_voted_for, term, voted_for)

    def _send_vote(self, peer: PeerId, req: "RequestVoteRequest",
                   on_resp) -> None:
        """Dispatch one RequestVote through the batched send plane when
        a NodeManager is wired (one ``multi_vote`` RPC per endpoint per
        flush — election herds at high group counts coalesce instead of
        spawning O(G x P) tasks), else a direct transient RPC task.
        ``on_resp(resp, peer)`` runs only when a response arrives;
        errors are silence, like a dropped packet."""
        if self.node_manager is not None:
            self.node_manager.send_plane.sender(peer.endpoint).submit_vote(
                self, req, lambda resp, p=peer: on_resp(resp, p))
            return

        async def direct():
            try:
                resp = await self.transport.request_vote(
                    peer.endpoint, req,
                    timeout_ms=self.options.election_timeout_ms)
            except RpcError:
                return
            await on_resp(resp, peer)

        t = asyncio.ensure_future(direct())
        t.add_done_callback(lambda tt: tt.cancelled() or tt.exception())

    async def _pre_vote(self) -> None:  # graftcheck: holds(_lock)
        """Pre-vote: probe electability WITHOUT bumping term (symmetric-
        partition tolerance — reference: NodeImpl#preVote)."""
        if self.log_manager.last_snapshot_id().index > 0 and \
                self.snapshot_executor and self.snapshot_executor.installing:
            return
        conf, old_conf = self.conf_entry.conf, self.conf_entry.old_conf
        ctx = _VoteCtx(conf, old_conf)
        ctx.grant(self.server_id)
        last_id = self.log_manager.last_log_id()
        term = self.current_term
        if ctx.is_granted():
            await self._elect_self()
            return
        req_term = term + 1  # NOT persisted

        async def on_resp(resp: RequestVoteResponse, peer: PeerId):
            async with self._lock:
                if (self.state != State.FOLLOWER or self.current_term != term):
                    return  # world moved on
                if resp.term > self.current_term:
                    await self._step_down(resp.term, Status.error(
                        RaftError.EHIGHERTERMRESPONSE, "pre-vote response"))
                    return
                if resp.granted:
                    ctx.grant(peer)
                    if ctx.is_granted():
                        await self._elect_self()

        for p in set(conf.peers) | set(old_conf.peers):
            if p != self.server_id:
                req = RequestVoteRequest(
                    group_id=self.group_id, server_id=str(self.server_id),
                    peer_id=str(p), term=req_term,
                    last_log_index=last_id.index, last_log_term=last_id.term,
                    pre_vote=True)
                self._send_vote(p, req, on_resp)

    async def _elect_self(self) -> None:  # graftcheck: holds(_lock)
        """Real election: term+1, vote for self, solicit votes.
        Caller must hold the lock."""
        conf, old_conf = self.conf_entry.conf, self.conf_entry.old_conf
        if not self.conf_entry.contains(self.server_id):
            return
        LOG.info("%s starting election at term %d", self, self.current_term + 1)
        RECORDER.record("election_start", self.group_id,
                        node=str(self.server_id),
                        term=self.current_term + 1)
        self.state = State.CANDIDATE
        self._ctrl.on_candidate()
        self.current_term += 1
        self.voted_for = self.server_id
        self.leader_id = EMPTY_PEER
        try:
            await self._persist_meta(self.current_term, self.server_id)
        except Exception:
            # ENOSPC/EIO mid self-vote save: abort the campaign cleanly
            # (no votes were solicited; a full disk must not kill the
            # node or campaign on an unpersisted term).  In-memory term
            # stays bumped, which is safe — it can only refuse stale
            # traffic — and the retry timer fires the next attempt.
            LOG.exception("%s election aborted: meta persist failed", self)
            self.state = State.FOLLOWER
            self._ctrl.on_follower()
            return
        term = self.current_term
        last_id = self.log_manager.last_log_id()
        # tally: TimerControl checks quorum inline per grant; the
        # engine's device tick tallies the granted row and fires
        # _on_engine_elected (start_vote_round only short-circuits the
        # single-voter case)
        if self._ctrl.start_vote_round():
            await self._become_leader()
            return

        async def on_resp(resp: RequestVoteResponse, peer: PeerId):
            async with self._lock:
                if self.state != State.CANDIDATE or self.current_term != term:
                    return
                if resp.term > self.current_term:
                    await self._step_down(resp.term, Status.error(
                        RaftError.EHIGHERTERMRESPONSE, "vote response"))
                    return
                if resp.granted and self._ctrl.grant_vote(peer):
                    await self._become_leader()

        for p in set(conf.peers) | set(old_conf.peers):
            if p != self.server_id:
                req = RequestVoteRequest(
                    group_id=self.group_id, server_id=str(self.server_id),
                    peer_id=str(p), term=term,
                    last_log_index=last_id.index, last_log_term=last_id.term,
                    pre_vote=False)
                self._send_vote(p, req, on_resp)

    async def _handle_vote_timeout(self) -> None:
        async with self._lock:
            if self.state != State.CANDIDATE:
                return
            if self.options.raft_options.step_down_when_vote_timedout:
                self._ctrl.stop_vote_wait()
                await self._step_down(self.current_term, Status.error(
                    RaftError.ERAFTTIMEDOUT, "vote timed out"))
            else:
                await self._elect_self()  # retry

    # -- engine-scheduled slow paths (EngineControl event masks) -----------

    async def _on_election_due(self) -> None:
        """Engine path: one deadline serves both the follower election
        timeout and the candidate vote-round timeout; each handler
        re-checks state under the lock, so at most one acts."""
        await self._handle_election_timeout()
        await self._handle_vote_timeout()

    async def _on_engine_elected(self) -> None:
        """Device tick saw a vote quorum in the granted row."""
        async with self._lock:
            if self.state != State.CANDIDATE:
                return
            if not self._ctrl.vote_quorum_now():
                return  # conf changed under the round; let it time out
            await self._become_leader()

    async def _on_engine_quorum_dead(self) -> None:
        """Device tick saw the quorum-ack age exceed the election
        timeout (the stepDownTimer analog)."""
        await self._check_dead_nodes()

    async def _become_leader(self) -> None:  # graftcheck: holds(_lock)
        """Caller holds the lock; we are CANDIDATE with a vote quorum."""
        self.state = State.LEADER
        self.leader_id = self.server_id
        self._ctrl.on_leader()
        LOG.info("%s became LEADER at term %d", self, self.current_term)
        RECORDER.record("leader_elected", self.group_id,
                        node=str(self.server_id), term=self.current_term)
        for peer in self.conf_entry.list_peers():
            if peer != self.server_id:
                self.replicators.add(peer)
        for learner in set(self.conf_entry.conf.learners) | set(
                self.conf_entry.old_conf.learners):
            self.replicators.add(learner)
        if self._note_attested is not None:
            # the leader's log is trivially consistent with itself
            self._note_attested(self.current_term)
        self.ballot_box.reset_pending_index(
            self.log_manager.last_log_index() + 1)
        # commit a CONFIGURATION entry for the current conf: safely commits
        # all prior-term entries (Raft §5.4.2; reference: becomeLeader)
        conf_entry = LogEntry(
            type=EntryType.CONFIGURATION,
            peers=list(self.conf_entry.conf.peers),
            learners=list(self.conf_entry.conf.learners) or None,
            old_peers=list(self.conf_entry.old_conf.peers) or None,
            old_learners=list(self.conf_entry.old_conf.learners) or None,
            witnesses=list(self.conf_entry.conf.witnesses) or None,
            old_witnesses=list(self.conf_entry.old_conf.witnesses) or None,
        )
        term = self.current_term
        last_id = self.log_manager.stage_leader_entries([conf_entry], term)
        # readIndex safety gate: a fresh leader's lastCommittedIndex is
        # carried over from follower time and may LAG entries the old
        # leader committed and acked — serving reads against it loses
        # acked writes (found by the linearizability soak).  Reads are
        # refused until this no-op (the first entry of OUR term) commits
        # (reference: ReadOnlyServiceImpl's ERAFTTIMEDOUT until the
        # leader commits in its current term).
        self._term_first_index = last_id.index
        if not self.conf_entry.old_conf.is_empty():
            # elected while a joint configuration is in flight (the old
            # leader died mid-change): adopt the change and drive it to
            # completion — without this, the conf entry just committed
            # above finds no ctx to advance and the group is wedged in
            # joint forever (reference: ConfigurationCtx#flush at
            # becomeLeader)
            self._conf_ctx = _ConfigurationCtx.resume_joint(
                self, self.conf_entry.old_conf.copy(),
                self.conf_entry.conf.copy(), joint_index=last_id.index)
            LOG.info("%s resuming joint membership change %s -> %s", self,
                     self.conf_entry.old_conf, self.conf_entry.conf)
        self.replicators.wake_all()
        self.fsm_caller.on_leader_start(term)
        asyncio.ensure_future(self._flush_and_self_commit(term, last_id.index))

    async def _flush_and_self_commit(self, term: int, index: int) -> None:
        try:
            await self.log_manager.flush_staged(index)
        except RaftException:
            # storage flush failed: the on_storage_error hook handles
            # the step-down; this fire-and-forget task must not die
            # with an unhandled exception
            return
        async with self._lock:
            if self.is_leader() and self.current_term == term:
                self._commit_at_self(index)

    def _on_log_storage_error(self, exc: BaseException) -> None:
        """LogManager on_storage_error hook (runs in the flush loop's
        except path): a flush that failed ENOSPC/EIO already failed its
        waiters with retryable EIO — here the LEADERSHIP is surrendered
        so clients re-route while the store sheds/reclaims, instead of
        the process dying or the leader lying about durability."""
        t = asyncio.ensure_future(self._step_down_on_storage_error(str(exc)))
        t.add_done_callback(lambda tt: tt.cancelled() or tt.exception())

    async def _step_down_on_storage_error(self, msg: str) -> None:
        async with self._lock:
            if self.state not in (State.LEADER, State.TRANSFERRING):
                return
            # same-term step-down: deliberately NOT a term bump — a
            # bump would persist meta, i.e. another write on the disk
            # that just refused one
            await self._step_down(
                self.current_term,
                Status.error(RaftError.EIO, f"log storage failed: {msg}"))

    # graftcheck: holds(_lock)
    async def _step_down(self, term: int, status: Status,
                         new_leader: PeerId = EMPTY_PEER) -> None:
        """Caller holds the lock (reference: NodeImpl#stepDown)."""
        if self.state in (State.ERROR, State.SHUTTING, State.SHUTDOWN):
            # ERROR is sticky: a straggler RPC response (e.g. an
            # in-flight heartbeat seeing a higher term) must not
            # resurrect a failed node into FOLLOWER with live timers
            return
        LOG.info("%s step down at term %d -> %d: %s", self, self.current_term,
                 term, status)
        RECORDER.record("step_down", self.group_id,
                        node=str(self.server_id), was=self.state.value,
                        term=self.current_term, to_term=term,
                        reason=status.error_msg[:80])
        was_leader = self.state in (State.LEADER, State.TRANSFERRING)
        self._ctrl.on_step_down(self.state == State.CANDIDATE, was_leader)
        if was_leader:
            self.replicators.stop_all()
            self.ballot_box.clear_pending()
            self._trace_quorum.clear()  # their quorum never happened here
            self.fsm_caller.fail_pending_closures(
                Status.error(RaftError.ENEWLEADER,
                             "leader stepped down: " + status.error_msg))
            self.fsm_caller.on_leader_stop(status)
        self.state = State.FOLLOWER
        self.leader_id = new_leader
        self._last_leader_timestamp = self._clock.monotonic()
        self._refresh_target_priority()
        if term > self.current_term:
            self.current_term = term
            self.voted_for = EMPTY_PEER
            await self._persist_meta(term, EMPTY_PEER)
        if self._conf_ctx is not None:
            self._conf_ctx.fail(Status.error(
                RaftError.ENEWLEADER, "leader stepped down"))
            self._conf_ctx = None
        self._ctrl.on_follower()

    async def step_down_on_higher_term(self, term: int, reason: str) -> None:
        async with self._lock:
            if term > self.current_term:
                await self._step_down(term, Status.error(
                    RaftError.EHIGHERTERMRESPONSE, reason))

    async def _check_dead_nodes(self) -> None:
        """Leader: step down if a quorum hasn't acked within the election
        timeout (asymmetric-partition tolerance — NodeImpl#checkDeadNodes).
        Scheduling: TimerControl's stepdown timer, or the engine tick's
        step_down mask; the age itself is re-verified here in both."""
        async with self._lock:
            if not self.is_leader():
                return
            if (self._ctrl.quorum_ack_age_s()
                    >= self.options.election_timeout_ms / 1000.0):
                await self._step_down(
                    self.current_term,
                    Status.error(RaftError.ERAFTTIMEDOUT,
                                 "quorum unreachable within election timeout"))
                return
            self._maybe_priority_transfer()

    def _maybe_priority_transfer(self) -> None:  # graftcheck: holds(_lock)
        """Priority RE-election (geo): a leader elected via target-
        priority decay (its zone's high-priority nodes were dead) hands
        leadership BACK once a higher-priority voter is healthy again —
        alive, caught up through the commit point, for
        ``priority_transfer_rounds`` consecutive stepdown-timer rounds.
        Leadership returns to the preferred (traffic-local) zone after
        it heals instead of sticking wherever the decay left it."""
        rounds = self.options.raft_options.priority_transfer_rounds
        my = self.server_id.priority
        if (rounds <= 0 or my == ElectionPriority.DISABLED
                or self.state != State.LEADER
                or self._conf_ctx is not None
                or not self.conf_entry.old_conf.is_empty()):
            self._priority_transfer_rounds = 0
            return
        conf = self.conf_entry.conf
        witnesses = set(conf.witnesses)
        candidates = [p for p in conf.peers
                      if p != self.server_id and p.priority > my
                      and p not in witnesses]
        if not candidates:
            self._priority_transfer_rounds = 0
            return
        best = max(candidates, key=lambda p: p.priority)
        alive = set(self._ctrl.alive_peers())
        r = self.replicators.get(best)
        if (best not in alive or r is None
                or r.match_index < self.ballot_box.last_committed_index):
            self._priority_transfer_rounds = 0
            return
        self._priority_transfer_rounds += 1
        if self._priority_transfer_rounds < rounds:
            return
        self._priority_transfer_rounds = 0
        LOG.info("%s priority re-election: transferring leadership to "
                 "higher-priority %s", self, best)
        self.metrics.counter("priority-transfers")
        # transfer_leadership_to takes the node lock itself — schedule it
        # (it re-validates leadership/conf state under the lock)
        t = asyncio.ensure_future(self.transfer_leadership_to(best))
        t.add_done_callback(lambda tt: tt.cancelled() or tt.exception())

    def leader_lease_is_valid(self) -> bool:
        """For LEASE_BASED reads: a quorum acked within lease window."""
        if not self.is_leader():
            return False
        return self._ctrl.lease_valid()

    # ======================================================================
    # RPC handlers (server side)
    # ======================================================================

    async def handle_request_vote(self, req: RequestVoteRequest
                                  ) -> RequestVoteResponse:
        candidate = PeerId.parse(req.server_id)
        async with self._lock:
            if self.state in (State.SHUTTING, State.SHUTDOWN, State.ERROR,
                              State.UNINITIALIZED):
                return RequestVoteResponse(term=self.current_term, granted=False)
            # a vote solicitation is protocol activity: a hibernating
            # group (leader included) resumes its timers — a woken
            # leader's next beat then re-absorbs the soliciting
            # follower instead of leaving it pre-voting forever against
            # a lease-fresh quorum
            self._ctrl.note_activity()
            if req.pre_vote:
                return self._handle_pre_vote(req, candidate)
            # real vote
            if req.term < self.current_term:
                return RequestVoteResponse(term=self.current_term, granted=False)
            if (not self.conf_entry.contains(candidate)
                    and self._believes_leader_alive()):
                # removed-server disruption guard (Raft §4.2.3): a voter
                # removed from the conf may keep timing out and soliciting
                # votes with ever-higher terms; while we have a live
                # leader, a non-member's request must not depose it (the
                # term bump in _step_down below is exactly the storm).
                # Without a live leader the request is processed normally
                # — a behind-the-conf node must not block recovery.
                return RequestVoteResponse(term=self.current_term, granted=False)
            if req.term > self.current_term:
                await self._step_down(req.term, Status.error(
                    RaftError.EHIGHERTERMREQUEST,
                    f"vote request from {candidate}"))
            log_ok = self._candidate_log_up_to_date(req)
            if (log_ok and self.voted_for.is_empty()
                    and self.state == State.FOLLOWER):
                self.voted_for = candidate
                try:
                    await self._persist_meta(self.current_term, candidate)
                except Exception:
                    # ENOSPC/EIO mid vote-save: the on-disk {term, vote}
                    # pair is intact (tmp+rename / journal tail never
                    # acked) and no grant left this node — forget the
                    # tentative in-memory vote and refuse; the
                    # candidate simply retries elsewhere.  Acking
                    # without durability would be a double-vote hazard
                    # after a crash.
                    LOG.exception("%s vote persist failed; refusing grant",
                                  self)
                    self.voted_for = EMPTY_PEER
                    return RequestVoteResponse(term=self.current_term,
                                               granted=False)
                self._last_leader_timestamp = self._clock.monotonic()  # grant => reset
                self._ctrl.note_leader_contact()
                return RequestVoteResponse(term=self.current_term, granted=True)
            granted = log_ok and self.voted_for == candidate
            return RequestVoteResponse(term=self.current_term, granted=granted)

    def _handle_pre_vote(self, req: RequestVoteRequest, candidate: PeerId
                         ) -> RequestVoteResponse:
        """Pre-vote grant: candidate's log >= ours, req.term >= ours, and we
        haven't heard from a live leader within the lease."""
        if req.term < self.current_term:
            return RequestVoteResponse(term=self.current_term, granted=False)
        if (not self.conf_entry.contains(candidate)
                and self._believes_leader_alive()):
            # removed-server noise (reference: NodeImpl#handlePreVoteRequest
            # membership check) — but ONLY while a live leader exists,
            # mirroring the real-vote guard below: with no leader, a
            # node whose conf is STALE (the entry adding the candidate
            # hasn't reached it yet) must still let the candidate
            # through pre-vote, or a {A,B,D} group where only B lags at
            # {A,B,C} can never elect D after A dies
            return RequestVoteResponse(term=self.current_term, granted=False)
        # role-aware liveness: a follower consults its leader-contact
        # lease (store-delegated while quiescent), the LEADER consults
        # its own quorum-ack lease — the follower-side timestamp is not
        # refreshed while leading, so the bare _leader_lease_valid()
        # would have a long-lived (or hibernating) leader grant
        # pre-votes against itself
        if self._believes_leader_alive():
            return RequestVoteResponse(term=self.current_term, granted=False)
        granted = self._candidate_log_up_to_date(req)
        return RequestVoteResponse(term=self.current_term, granted=granted)

    def _candidate_log_up_to_date(self, req: RequestVoteRequest) -> bool:
        last = self.log_manager.last_log_id()
        return (req.last_log_term, req.last_log_index) >= (last.term, last.index)

    async def handle_append_entries(self, req: AppendEntriesRequest
                                    ) -> AppendEntriesResponse:
        server = PeerId.parse(req.server_id)
        # capability advertisement (VERDICT r2 #6): this endpoint serves
        # multi_heartbeat iff it runs a NodeManager
        mh = self.node_manager is not None
        async with self._lock:
            if self.state in (State.SHUTTING, State.SHUTDOWN, State.ERROR,
                              State.UNINITIALIZED):
                # NOT a protocol response: a success=False/last=0 reply
                # here reads as "my log is empty" and drives the leader
                # into a full-speed probe livelock at next_index=1.  An
                # RPC error takes the leader's paced-retry path instead.
                raise RpcError(Status.error(
                    RaftError.EHOSTDOWN, f"node not serviceable: "
                    f"{self.state.value}"))
            if req.term < self.current_term:
                return AppendEntriesResponse(
                    multi_hb=mh,
                    term=self.current_term, success=False,
                    last_log_index=self.log_manager.last_log_index())
            if req.term > self.current_term or self.state != State.FOLLOWER:
                await self._step_down(req.term, Status.error(
                    RaftError.EHIGHERTERMREQUEST,
                    f"append_entries from {server}"), new_leader=server)
            if self.leader_id.is_empty():
                self.leader_id = server
                self.fsm_caller.on_start_following(server, req.term)
            elif self.leader_id != server:
                # two leaders in one term: protocol violation
                LOG.error("%s: leader conflict %s vs %s at term %d", self,
                          self.leader_id, server, req.term)
                await self._step_down(req.term + 1, Status.error(
                    RaftError.ELEADERCONFLICT, "two leaders in one term"))
                return AppendEntriesResponse(
                    multi_hb=mh,
                    term=self.current_term, success=False,
                    last_log_index=self.log_manager.last_log_index())
            self._last_leader_timestamp = self._clock.monotonic()
            self._ctrl.note_leader_contact()
            # an incoming full-semantics append (entries, probe, or
            # classic beat) means the leader is ACTIVE: a quiescent
            # follower wakes — heals the asymmetric state left by an
            # aborted quiesce handshake within one beat instead of one
            # store-lease expiry
            self._ctrl.note_activity()

            lm = self.log_manager
            if not req.entries:
                # heartbeat / probe
                local_prev_term = lm.get_term(req.prev_log_index)
                if req.prev_log_index > lm.last_log_index() or (
                        req.prev_log_index >= lm.first_log_index() - 1
                        and local_prev_term != req.prev_log_term
                        and req.prev_log_index != lm.last_snapshot_id().index):
                    # term mismatch (not merely a short log): tell the
                    # leader where our conflicting term run starts
                    hint = 0
                    if (req.prev_log_index <= lm.last_log_index()
                            and local_prev_term != 0):
                        hint = lm.conflict_hint(req.prev_log_index,
                                                local_prev_term)
                    return AppendEntriesResponse(
                        multi_hb=mh,
                        term=self.current_term, success=False,
                        last_log_index=lm.last_log_index(),
                        conflict_index=hint)
                self.ballot_box.set_last_committed_index(
                    min(req.committed_index, req.prev_log_index))
                if self._note_attested is not None and \
                        req.prev_log_index >= lm.last_log_index():
                    # heartbeat AT our tail: whole log prefix-matches
                    # the leader's (replica-plane attestation)
                    self._note_attested(req.term)
                return AppendEntriesResponse(
                    multi_hb=mh,
                    term=self.current_term, success=True,
                    last_log_index=lm.last_log_index())

            if self._note_append_start is not None:
                self._note_append_start(req.term)
            entries = list(req.entries)
            if self.options.witness:
                # metadata-only journal: strip any payload that still
                # arrived full (a mixed-fleet leader that predates
                # witness-aware stripping) — CRC-verify the wire blob
                # FIRST so a corrupt frame can't journal bad metadata
                from tpuraft.entity import strip_entry_payload

                entries = [strip_entry_payload(e) for e in entries]
            # trace plane: wire-borne contexts join the follower-side
            # append (incl. its fsync wait) to the originating trace
            tr0 = 0.0
            if TRACER.enabled and req.trace_ctx:
                adopt_entry_ctx(entries, req.trace_ctx)
                tr0 = time.perf_counter()
            try:
                ok = await lm.append_entries_follower(
                    req.prev_log_index, req.prev_log_term, entries)
            except RaftException as e:
                if e.status.code == RaftError.EIO:
                    # transient storage failure (ENOSPC/EIO flush): the
                    # entries were NOT journaled and NOT acked — reject
                    # the round so the leader backs off and retries.
                    # Once pressure clears (reclaim freed disk, burst
                    # healed) the retry lands; the replica must NOT be
                    # condemned to ERROR for a full volume.
                    return AppendEntriesResponse(
                        multi_hb=mh,
                        term=self.current_term, success=False,
                        last_log_index=lm.last_log_index())
                # conflict below the applied index: this replica's state
                # machine has diverged from the leader's committed log —
                # unrecoverable (only reachable through storage loss /
                # amnesiac restart, which Raft does not tolerate).  Fail
                # the node loudly (reference: NodeImpl#onError) instead
                # of rejecting this RPC forever.  The FSM hears about it
                # too (StateMachine#onError) via the caller queue; the
                # ERROR transition itself happens now, under the lock,
                # so no further RPC is served meanwhile.
                self._enter_error_locked(e.status)
                self.fsm_caller.poison(e.status)
                raise RpcError(Status.error(
                    RaftError.EHOSTDOWN,
                    f"node failed: {e.status}")) from e
            if tr0:
                t1 = time.perf_counter()
                for e in entries:
                    if e.trace_id:
                        TRACER.span(e.trace_id, "follower_append", tr0, t1,
                                    proc=self._trace_proc, ok=ok)
            if not ok:
                return AppendEntriesResponse(
                    multi_hb=mh,
                    term=self.current_term, success=False,
                    last_log_index=lm.last_log_index())
            self._refresh_conf_from_log()
            self.ballot_box.set_last_committed_index(
                min(req.committed_index,
                    req.prev_log_index + len(req.entries)))
            if self._note_attested is not None and \
                    lm.last_log_index() == req.prev_log_index + len(req.entries):
                # the append covered our tail: log is a verified prefix
                # of the leader's (replica-plane attestation)
                self._note_attested(req.term)
            return AppendEntriesResponse(
                multi_hb=mh,
                term=self.current_term, success=True,
                last_log_index=lm.last_log_index())

    def _refresh_conf_from_log(self) -> None:  # graftcheck: holds(_lock)
        last = self.log_manager.conf_manager.last()
        if last.conf.is_empty():
            # no conf anywhere in log/snapshot: if ours came from a log
            # entry that a conflict truncation just removed, roll back to
            # the boot conf instead of keeping a phantom membership
            if self.conf_entry.id.index > self.log_manager.last_log_index():
                self._apply_conf_entry(ConfigurationEntry(
                    LogId(0, 0), self.options.initial_conf.copy()))
            return
        if (last.id.index == self.conf_entry.id.index
                and last.id.term == self.conf_entry.id.term):
            return
        # forward: a newer conf entry was appended.  BACKWARD: the entry
        # our conf came from was truncated away (new-leader conflict
        # resolution) — the membership must follow the log both ways, or
        # a follower keeps voting under a conf that no longer exists.
        # SAME INDEX, different term: conflict resolution REPLACED our
        # conf entry with another leader's — adopt the replacement.
        self._apply_conf_entry(last)

    def _apply_conf_entry(self, entry: ConfigurationEntry) -> None:  # graftcheck: holds(_lock)
        self.conf_entry = entry
        self.ballot_box.update_conf(entry.conf, entry.old_conf)
        self._refresh_target_priority()
        if not self.options.witness and (
                entry.conf.is_witness(self.server_id)
                or entry.old_conf.is_witness(self.server_id)):
            self._adopt_witness_mode()

    def _adopt_witness_mode(self) -> None:  # graftcheck: holds(_lock)
        """The committed conf flags THIS node a witness but it was not
        booted as one (runtime ``add-witness`` against a plain-booted
        node): adopt the role now — swap in the null FSM and raise the
        flag every witness gate (campaign / TimeoutNow / reads)
        consults.  Whatever the real FSM applied during catch-up
        (payload-stripped entries) is quarantined: witness state is
        never served, and a witness can never be elected over, so the
        divergence is unobservable.  Prefer booting the process with
        the '/witness' conf suffix so the role holds from the first
        applied entry."""
        from tpuraft.core.state_machine import WitnessStateMachine

        LOG.warning("%s adopting WITNESS mode from the committed conf "
                    "(boot flag was missing — start this node with a "
                    "'/witness' peer suffix)", self)
        self.options.witness = True
        self.options.fsm = WitnessStateMachine()
        self.fsm_caller.replace_fsm(self.options.fsm)

    async def handle_timeout_now(self, req: TimeoutNowRequest
                                 ) -> TimeoutNowResponse:
        """Leadership transfer target: elect immediately, skipping pre-vote
        (reference: NodeImpl#handleTimeoutNowRequest)."""
        async with self._lock:
            if req.term != self.current_term or self.state != State.FOLLOWER:
                return TimeoutNowResponse(term=self.current_term, success=False)
            if self.options.witness:
                # never campaigns — even on an explicit transfer nudge
                # (a mixed-fleet leader that missed the witness flag)
                return TimeoutNowResponse(term=self.current_term,
                                          success=False)
            from tpuraft.util.health import SICK

            health = self.options.health
            if health is not None and health.score() == SICK:
                # gray-failure guard: a SICK store must not ACCEPT
                # leadership either — without this, two slow stores
                # evacuating at each other ping-pong every lease (the
                # mutual-evacuation storm the gray A/B bench caught).
                # Always safe: a refused transfer just times out and
                # the old leader's watchdog resumes.
                LOG.info("%s refusing TimeoutNow: local store is SICK",
                         self)
                return TimeoutNowResponse(term=self.current_term,
                                          success=False)
            await self._elect_self()
            return TimeoutNowResponse(term=self.current_term, success=True)

    async def handle_install_snapshot(self, req):
        from tpuraft.rpc.messages import InstallSnapshotResponse

        if self.state in (State.SHUTTING, State.SHUTDOWN, State.ERROR,
                          State.UNINITIALIZED):
            # same contract as handle_append_entries: a failed node must
            # not load snapshots into its (poisoned) state machine
            raise RpcError(Status.error(
                RaftError.EHOSTDOWN, f"node not serviceable: "
                f"{self.state.value}"))
        if not self.snapshot_executor:
            return InstallSnapshotResponse(term=self.current_term, success=False)
        return await self.snapshot_executor.handle_install_snapshot(req)

    async def handle_read_index(self, req: ReadIndexRequest) -> ReadIndexResponse:
        """Follower-forwarded readIndex: only the leader serves it.  A
        rejection carries this node's current leader hint (trailing wire
        field) so the forwarder re-probes the real leader within its
        attempt instead of surfacing a terminal error."""
        if not self.is_leader():
            return ReadIndexResponse(index=0, success=False,
                                     term=self.current_term,
                                     leader_hint=str(self.leader_id)
                                     if not self.leader_id.is_empty()
                                     else "")
        try:
            idx = await self.read_only_service.leader_confirm_read_index()
            # LEASE mode serves the fence without any beat round, so the
            # forwarding follower may sit on the committed ENTRIES but
            # not the commit KNOWLEDGE until the next periodic beat (up
            # to one heartbeat interval — observed as ~1s forwarded-read
            # stalls in its local wait_applied).  Push one beat at it
            # now; the beat's prev-log check makes the commit transfer
            # safe where blindly adopting the bare index would not be
            # (a divergent-tail follower must never commit its own
            # stale entries at the leader's index).  SAFE mode skips
            # this: its confirmation round just beat every follower.
            if (self.options.raft_options.read_only_option
                    == ReadOnlyOption.LEASE_BASED):
                r = self.replicators.get(PeerId.parse(req.server_id))
                if r is not None and r.match_index >= idx:
                    t = asyncio.ensure_future(r.send_heartbeat())
                    t.add_done_callback(
                        lambda tt: tt.cancelled() or tt.exception())
            return ReadIndexResponse(index=idx, success=True,
                                     term=self.current_term)
        except Exception:
            return ReadIndexResponse(index=0, success=False,
                                     term=self.current_term)

    # ======================================================================
    # membership change (reference: ConfigurationCtx — SURVEY.md §3.1)
    # ======================================================================

    async def add_peer(self, peer: PeerId, witness: bool = False) -> Status:
        new_conf = self.conf_entry.conf.copy()
        if new_conf.contains(peer):
            return Status.error(RaftError.EEXISTS, f"{peer} already in conf")
        new_conf.peers.append(peer)
        if witness:
            new_conf.witnesses.append(peer)
        return await self.change_peers(new_conf)

    async def remove_peer(self, peer: PeerId) -> Status:
        new_conf = self.conf_entry.conf.copy()
        if not new_conf.contains(peer):
            return Status.error(RaftError.ENOENT, f"{peer} not in conf")
        new_conf.peers.remove(peer)
        if peer in new_conf.witnesses:
            new_conf.witnesses.remove(peer)
        return await self.change_peers(new_conf)

    def peer_is_witness(self, peer: PeerId) -> bool:
        """Is ``peer`` a witness in the current conf OR in an in-flight
        membership change's target conf?  The ctx check matters during
        CATCHING_UP: a freshly added witness is not in conf yet, but its
        catch-up stream must already be payload-stripped — shipping the
        full log to a metadata-only replica wastes exactly the WAN
        bytes witnesses exist to save."""
        e = self.conf_entry
        if e.conf.is_witness(peer) or e.old_conf.is_witness(peer):
            return True
        ctx = self._conf_ctx
        return ctx is not None and ctx.new_conf.is_witness(peer)

    async def add_learners(self, learners: list[PeerId]) -> Status:
        new_conf = self.conf_entry.conf.copy()
        for l in learners:
            if l not in new_conf.learners:
                new_conf.learners.append(l)
        return await self.change_peers(new_conf)

    async def remove_learners(self, learners: list[PeerId]) -> Status:
        new_conf = self.conf_entry.conf.copy()
        new_conf.learners = [l for l in new_conf.learners if l not in learners]
        return await self.change_peers(new_conf)

    async def reset_learners(self, learners: list[PeerId]) -> Status:
        """Replace the learner set atomically (reference: `[1.3+]`
        CliServiceImpl#resetLearners)."""
        new_conf = self.conf_entry.conf.copy()
        new_conf.learners = list(dict.fromkeys(learners))
        return await self.change_peers(new_conf)

    async def change_peers(self, new_conf: Configuration) -> Status:
        """Arbitrary configuration change via joint consensus."""
        async with self._lock:
            if self.state == State.TRANSFERRING:
                return Status.error(RaftError.EBUSY,
                                    "leadership transferring; retry")
            if self.state != State.LEADER:
                return Status.error(RaftError.EPERM, "not leader")
            if self._conf_ctx is not None:
                return Status.error(
                    RaftError.EBUSY,
                    f"another membership change in progress "
                    f"(stage={self._conf_ctx.stage}); retry")
            if not new_conf.is_valid():
                return Status.error(RaftError.EINVAL, f"invalid conf {new_conf}")
            cur = self.conf_entry.conf
            converted = [p for p in new_conf.peers if cur.contains(p)
                         and cur.is_witness(p) != new_conf.is_witness(p)]
            if converted:
                # in-place witness<->data conversion is UNSAFE both
                # ways: a witness promoted to data voter serves from a
                # payload-less journal; a data voter demoted to witness
                # keeps a stale full journal the commit clamp would
                # trust.  Remove, wipe, re-add in the new role.
                return Status.error(
                    RaftError.EINVAL,
                    f"in-place witness/data role conversion of "
                    f"{[str(p) for p in converted]}: remove the peer, "
                    f"wipe its storage, then re-add it in the new role")
            if new_conf == self.conf_entry.conf:
                return Status.OK()
            ctx = _ConfigurationCtx(self, self.conf_entry.conf.copy(), new_conf)
            self._conf_ctx = ctx
            await ctx.start()
        try:
            return await ctx.wait()
        finally:
            async with self._lock:
                if self._conf_ctx is ctx:
                    if ctx.stage in ("none", "catching_up"):
                        # caller CANCELLED (operator timeout) before any
                        # entry was appended: abort cleanly — detaching a
                        # live ctx would let a slow catch-up later append
                        # a joint entry nothing drives, while a second
                        # change starts concurrently
                        ctx.fail(Status.error(
                            RaftError.ECANCELED, "change_peers caller gone"))
                        # tear down the replicators provisioned for the
                        # catch-up peers (mirrors the ECATCHUP abort):
                        # a leaked one would keep shipping to a
                        # non-member, and — worse — a retry of the same
                        # change would reuse its stale match_index and
                        # pass catch-up instantly even if the peer was
                        # wiped meanwhile.  Safe here ONLY because
                        # _conf_ctx is still ctx under the lock: no
                        # concurrent change can own these peers yet.
                        ctx._teardown_added_replicators()
                        self._conf_ctx = None
                    elif ctx.stage in ("done", "aborted"):
                        self._conf_ctx = None
                    # joint/stable with the caller gone: the entries are
                    # in the log — leave the ctx attached to drive the
                    # change to completion; _finish clears the slot

    async def reset_peers(self, new_conf: Configuration) -> Status:
        """Unsafe manual override when quorum is permanently lost
        (reference: Node#resetPeers)."""
        async with self._lock:
            if self.state in (State.ERROR, State.SHUTTING, State.SHUTDOWN,
                              State.UNINITIALIZED):
                # a failed node can't be revived by conf surgery — and
                # the sticky-ERROR _step_down would silently skip the
                # term bump while conf had already mutated
                return Status.error(
                    RaftError.EHOSTDOWN,
                    f"cannot reset peers in state {self.state.value}")
            if not new_conf.is_valid():
                return Status.error(RaftError.EINVAL, str(new_conf))
            self.conf_entry = ConfigurationEntry(
                LogId(0, self.current_term), new_conf.copy())
            self.ballot_box.update_conf(new_conf, Configuration())
            await self._step_down(self.current_term + 1, Status.error(
                RaftError.ESETPEER, "reset_peers"))
            return Status.OK()

    async def _on_configuration_applied(self, entry: LogEntry) -> None:
        """A CONFIGURATION entry committed+applied: advance the change ctx."""
        async with self._lock:
            self._refresh_conf_from_log()
            if self._conf_ctx is not None:
                await self._conf_ctx.on_committed(entry)

    # ======================================================================
    # snapshot plumbing (filled by SnapshotExecutor)
    # ======================================================================

    async def install_snapshot_on(self, peer: PeerId, replicator: Replicator
                                  ) -> bool:
        if not self.snapshot_executor:
            LOG.error("%s: peer %s needs snapshot but none configured",
                      self, peer)
            return False
        return await self.snapshot_executor.send_install_snapshot(
            peer, replicator)

    async def _handle_snapshot_timeout(self) -> None:
        if self.snapshot_executor:
            await self.snapshot_executor.do_snapshot()

    async def _on_snapshot_due(self) -> None:
        """Engine path: the device tick's snap_due mask fired for this
        group (the snapshotTimer analog — SURVEY §3.1 Timers)."""
        await self._handle_snapshot_timeout()

    async def _on_fsm_error(self, status: Status) -> None:
        async with self._lock:
            self._enter_error_locked(status)

    def _enter_error_locked(self, status: Status) -> None:
        """Transition to ERROR state; caller holds the node lock."""
        if self.state in (State.SHUTTING, State.SHUTDOWN, State.ERROR):
            return
        LOG.error("%s entering ERROR state: %s", self, status)
        RECORDER.record("node_error", self.group_id,
                        node=str(self.server_id),
                        status=str(status)[:120])
        if self.is_leader():
            self.replicators.stop_all()
            self.fsm_caller.fail_pending_closures(status)
        self.state = State.ERROR
        self._ctrl.deactivate()
        if self._snapshot_timer:
            self._snapshot_timer.stop()

    def __str__(self) -> str:
        return f"Node<{self.group_id}/{self.server_id}>"


# graftcheck: loop-confined — every method runs under the node lock on
# the node's loop (see class docstring termination discipline)
# graftcheck: called-under(_lock) — the ctx is driven exclusively from
# node paths that already hold the node lock (change_peers, on_committed
# apply, step-down teardown), so its cross-object calls into
# holds-annotated Node methods inherit the held lock
class _ConfigurationCtx:
    """Membership-change state machine: CATCHING_UP -> JOINT -> STABLE.

    Reference: NodeImpl's inner ConfigurationCtx (SURVEY.md §3.1/§4.3).

    Termination discipline (chaos-hardened): every exit path —
    completion, catch-up timeout, step-down, shutdown — moves ``stage``
    to a terminal value ("stable" or "aborted") and resolves ``_done``
    exactly once.  ``fail()`` marking the stage terminal is load-bearing:
    a catch-up waiter resolving True *concurrently* with a step-down
    would otherwise re-enter ``_enter_joint`` on a node that is no
    longer leader and append a joint entry to a FOLLOWER's log.
    """

    def __init__(self, node: Node, old_conf: Configuration,
                 new_conf: Configuration):
        self._node = node
        self.old_conf = old_conf
        self.new_conf = new_conf
        self.stage = "none"
        self._done: asyncio.Future = asyncio.get_running_loop().create_future()
        self._joint_index = 0
        self._stable_index = 0
        self._added: list[PeerId] = []

    @classmethod
    def resume_joint(cls, node: Node, old_conf: Configuration,
                     new_conf: Configuration,
                     joint_index: int) -> "_ConfigurationCtx":
        """A freshly elected leader found a joint conf in its log: build
        a ctx already in the joint stage, keyed to the conf entry the
        leader just staged for its own term, so the commit of that entry
        advances the change to stable instead of wedging the group in
        joint forever (reference: ConfigurationCtx#flush)."""
        ctx = cls(node, old_conf, new_conf)
        ctx._set_stage("joint")
        ctx._joint_index = joint_index
        return ctx

    def _set_stage(self, stage: str) -> None:
        self.stage = stage
        RECORDER.record("conf_stage", self._node.group_id,
                        node=str(self._node.server_id), stage=stage)
        listener = self._node.conf_stage_listener
        if listener is not None:
            try:
                listener(self._node, stage)
            except Exception:
                LOG.exception("conf stage listener failed at %s", stage)

    async def start(self) -> None:
        """Called under node lock."""
        node = self._node
        added = [p for p in self.new_conf.peers
                 if not self.old_conf.contains(p)]
        added += [l for l in self.new_conf.learners
                  if l not in self.old_conf.learners
                  and not self.old_conf.contains(l)]
        if not added:
            await self._enter_joint()
            return
        self._set_stage("catching_up")
        self._added = list(added)
        waiters = []
        for peer in added:
            r = node.replicators.add(peer)  # replicate as learner during catch-up
            waiters.append(r.wait_caught_up(
                node.options.catchup_margin,
                node.options.election_timeout_ms * 10 / 1000.0))
        asyncio.ensure_future(self._wait_catchup(waiters))

    async def _wait_catchup(self, waiters) -> None:
        results = await asyncio.gather(*waiters, return_exceptions=True)
        node = self._node
        async with node._lock:
            if self.stage != "catching_up":
                return  # aborted (step-down/shutdown) while we gathered
            if not all(r is True for r in results):
                # clean abort: tear down the replicators provisioned for
                # the peers that never caught up, so the next change
                # starts from scratch instead of inheriting stuck state
                self._teardown_added_replicators()
                self.fail(Status.error(RaftError.ECATCHUP,
                                       "new peers failed to catch up"))
                if node._conf_ctx is self:
                    node._conf_ctx = None
                return
            await self._enter_joint()

    def _teardown_added_replicators(self) -> None:
        """Remove replicators added for catch-up peers that are not part
        of the committed configuration (under node lock)."""
        node = self._node
        for peer in self._added:
            if (not node.conf_entry.contains(peer)
                    and peer not in node.conf_entry.conf.learners
                    and peer not in node.conf_entry.old_conf.learners):
                node.replicators.remove(peer)

    async def _enter_joint(self) -> None:
        """Append the joint-consensus CONFIGURATION entry (under lock)."""
        node = self._node
        self._set_stage("joint")
        in_joint = self.old_conf.peers != self.new_conf.peers
        entry = LogEntry(
            type=EntryType.CONFIGURATION,
            peers=list(self.new_conf.peers),
            old_peers=list(self.old_conf.peers) if in_joint else None,
            learners=list(self.new_conf.learners) or None,
            old_learners=(list(self.old_conf.learners) or None)
            if in_joint else None,
            witnesses=list(self.new_conf.witnesses) or None,
            old_witnesses=(list(self.old_conf.witnesses) or None)
            if in_joint else None,
        )
        term = node.current_term
        last_id = node.log_manager.stage_leader_entries([entry], term)
        self._joint_index = last_id.index
        node.conf_entry = ConfigurationEntry(
            last_id, self.new_conf.copy(),
            self.old_conf.copy() if in_joint else Configuration())
        node.ballot_box.update_conf(node.conf_entry.conf,
                                    node.conf_entry.old_conf)
        node._refresh_target_priority()
        # new peers may now vote/commit; replicators for removed peers keep
        # running until the change commits
        node.replicators.wake_all()
        asyncio.ensure_future(node._flush_and_self_commit(term, last_id.index))

    async def on_committed(self, entry: LogEntry) -> None:
        """A conf entry applied (under node lock)."""
        node = self._node
        if self.stage == "joint" and entry.id.index == self._joint_index:
            if entry.old_peers:
                # leave joint: append the stable (new-conf-only) entry
                self._set_stage("stable")
                stable = LogEntry(
                    type=EntryType.CONFIGURATION,
                    peers=list(self.new_conf.peers),
                    learners=list(self.new_conf.learners) or None,
                    witnesses=list(self.new_conf.witnesses) or None,
                )
                term = node.current_term
                last_id = node.log_manager.stage_leader_entries([stable], term)
                self._stable_index = last_id.index
                node.conf_entry = ConfigurationEntry(
                    last_id, self.new_conf.copy())
                node.ballot_box.update_conf(node.conf_entry.conf,
                                            node.conf_entry.old_conf)
                node._refresh_target_priority()
                node.replicators.wake_all()
                asyncio.ensure_future(
                    node._flush_and_self_commit(term, last_id.index))
            else:
                await self._finish()
        elif self.stage == "stable" and entry.id.index == self._stable_index:
            await self._finish()

    async def _finish(self) -> None:
        node = self._node
        self._set_stage("done")
        # retire replicators for peers no longer in conf: keep shipping
        # until the removed peer has RECEIVED the conf entry that removes
        # it (so it learns its removal and stops starting elections
        # against the survivors), then stop — bounded by a timeout for
        # peers that are dead or partitioned away
        final_index = self._stable_index or self._joint_index
        for peer in list(node.replicators.peers()):
            if not node.conf_entry.contains(peer) and \
                    peer not in node.conf_entry.conf.learners:
                node.replicators.retire(
                    peer, final_index,
                    node.options.election_timeout_ms * 4 / 1000.0)
        if not self._done.done():
            self._done.set_result(Status.OK())
        # clear the slot HERE, not only in change_peers' finally: a
        # resumed ctx (joint adopted at election) has no change_peers
        # caller, and a dangling ctx means EBUSY forever
        if node._conf_ctx is self:
            node._conf_ctx = None
        # leader removed itself: step down
        if not node.conf_entry.conf.contains(node.server_id):
            await node._step_down(node.current_term, Status.error(
                RaftError.ELEADERREMOVED, "leader removed from configuration"))

    def fail(self, status: Status) -> None:
        if self.stage not in ("done", "aborted"):
            self._set_stage("aborted")
        if not self._done.done():
            self._done.set_result(status)

    async def wait(self) -> Status:
        return await self._done
