"""RaftGroupService: boot one raft group member on a shared endpoint.

Reference parity: ``core:RaftGroupService`` (SURVEY.md §4.1).
"""

from __future__ import annotations

from tpuraft.core.node import Node
from tpuraft.core.node_manager import NodeManager
from tpuraft.entity import PeerId
from tpuraft.options import NodeOptions


class RaftGroupService:
    def __init__(self, group_id: str, server_id: PeerId, options: NodeOptions,
                 node_manager: NodeManager, transport,
                 ballot_box_factory=None):
        self.group_id = group_id
        self.server_id = server_id
        self.options = options
        self.node_manager = node_manager
        self.transport = transport
        self.ballot_box_factory = ballot_box_factory
        self.node: Node | None = None

    async def start(self) -> Node:
        node = Node(self.group_id, self.server_id, self.options, self.transport,
                    ballot_box_factory=self.ballot_box_factory)
        node.node_manager = self.node_manager  # for snapshot file service
        self.node_manager.add(node)
        ok = await node.init()
        if not ok:
            self.node_manager.remove(node)
            raise RuntimeError(f"node init failed: {node}")
        self.node = node
        return node

    async def join(self) -> None:
        """Block until the node has fully shut down (reference:
        RaftGroupService#join)."""
        if self.node is not None:
            await self.node.join()

    async def shutdown(self) -> None:
        if self.node:
            await self.node.shutdown()
            self.node_manager.remove(self.node)
            self.node = None
