"""L4 consensus core (reference: core:core/ — SURVEY.md §2).

Host-side protocol envelope around the device-plane math in tpuraft.ops:
Node (election/replication/membership), BallotBox (quorum commit),
FSMCaller (serialized user-state-machine callbacks), Replicator (per-peer
log shipping), ReadOnlyService (linearizable reads), NodeManager (multi-
group routing), RaftGroupService (bootstrap).
"""

from tpuraft.core.state_machine import StateMachine, StateMachineAdapter, Iterator
from tpuraft.core.node import Node, State
from tpuraft.core.node_manager import NodeManager
from tpuraft.core.raft_group_service import RaftGroupService

__all__ = [
    "StateMachine",
    "StateMachineAdapter",
    "Iterator",
    "Node",
    "State",
    "NodeManager",
    "RaftGroupService",
]
