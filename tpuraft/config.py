"""YAML configuration layer over the options dataclass tree.

Reference parity (SURVEY.md §6 "Config / flag system"): the reference
pairs its nested builder/POJO options with YAML files in the examples
(`RheaKVStoreOptions` + `configured/*` fluent builders); round 1 shipped
the dataclass tree only (VERDICT r1 partial, §6 row).  This module is
the YAML half: a strict hydrator from a YAML mapping onto any options
dataclass — nested dataclasses recurse, enums accept their value
strings, unknown keys raise (a typo'd tunable silently ignored is how
production clusters end up running defaults).

    node:
      election_timeout_ms: 1500
      log_uri: multilog:///data/raft/mlog#g1
      raft_options:
        max_inflight_msgs: 128
        read_only_option: lease_based
      tick:
        max_groups: 4096
        backend: auto

    opts = load_node_options("cluster.yaml")          # whole file
    opts = node_options_from_dict(doc["node"])        # sub-mapping
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Type, TypeVar, get_args, get_origin, get_type_hints

from tpuraft.conf import Configuration
from tpuraft.options import NodeOptions

T = TypeVar("T")


def hydrate(cls: Type[T], data: dict, path: str = "") -> T:
    """Build dataclass ``cls`` from a mapping, strictly: every key must
    name a field; nested dataclasses take nested mappings; Enum fields
    accept the enum's value (e.g. ``lease_based``); a ``Configuration``
    field accepts the peer-list string form ``"ip:port,ip:port,..."``."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls!r} is not an options dataclass")
    if not isinstance(data, dict):
        raise TypeError(f"{path or cls.__name__}: expected a mapping, "
                        f"got {type(data).__name__}")
    hints = _hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    for key, value in data.items():
        if key not in fields:
            known = ", ".join(sorted(fields))
            raise KeyError(
                f"{path + key if path else key}: unknown option "
                f"(known: {known})")
        ftype = hints.get(key, fields[key].type)
        kwargs[key] = _convert(ftype, value, f"{path}{key}.")
    return cls(**kwargs)


def _hints(cls: type) -> dict:
    """get_type_hints resilient to TYPE_CHECKING-only forward refs
    (e.g. NodeOptions.fsm: Optional["StateMachine"]): unresolvable
    names degrade to `object` — they are runtime-constructed values a
    YAML file can't express anyway."""
    localns: dict[str, Any] = {}
    for _ in range(8):
        try:
            return get_type_hints(cls, localns=localns)
        except NameError as e:
            if not getattr(e, "name", None):
                return {}
            localns[e.name] = object
    return {}


def _convert(ftype: Any, value: Any, path: str) -> Any:
    import types

    origin = get_origin(ftype)
    if origin is typing.Union or origin is types.UnionType:  # X | None too
        args = [a for a in get_args(ftype) if a is not type(None)]
        if value is None:
            return None
        if len(args) == 1:  # Optional[X]
            return _convert(args[0], value, path)
        return value
    if origin in (list, tuple):
        args = get_args(ftype)
        elem = args[0] if args else None
        if elem is not None and isinstance(value, (list, tuple)):
            return [
                _convert(elem, v, f"{path}[{i}].")
                for i, v in enumerate(value)
            ]
        return value
    if origin is not None:
        return value
    if isinstance(ftype, type):
        if ftype is Configuration and isinstance(value, str):
            return Configuration.parse(value)
        if dataclasses.is_dataclass(ftype):
            return hydrate(ftype, value, path)
        if issubclass(ftype, enum.Enum):
            if isinstance(value, ftype):
                return value
            for member in ftype:
                if value in (member.value, member.name,
                             str(member.name).lower()):
                    return member
            raise ValueError(
                f"{path[:-1]}: {value!r} is not one of "
                f"{[m.value for m in ftype]}")
        # bool is an int subclass: YAML 1.1 parses on/yes as True, and
        # letting it hydrate an int field silently collapses tunables
        # (max_inflight_msgs: on -> 1) instead of erroring
        if ftype in (int, float) and isinstance(value, bool):
            raise TypeError(
                f"{path[:-1]}: expected {ftype.__name__}, got bool "
                f"({value!r})")
        if ftype is float and isinstance(value, int):
            return float(value)
        if ftype in (int, float, str, bool) and not isinstance(value, ftype):
            raise TypeError(
                f"{path[:-1]}: expected {ftype.__name__}, "
                f"got {type(value).__name__} ({value!r})")
    return value


def node_options_from_dict(doc: dict) -> NodeOptions:
    return hydrate(NodeOptions, doc)


def load_node_options(path: str, key: str = "node") -> NodeOptions:
    """Read a YAML file; hydrate NodeOptions from its ``key`` mapping
    (or the whole document when ``key`` is absent/empty).  When ``key``
    is selected, sibling top-level keys are an error — a misindented
    section silently running defaults is the exact failure this strict
    layer exists to prevent."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if key and key in doc:
        extra = sorted(k for k in doc if k != key)
        if extra:
            raise KeyError(
                f"{path}: unexpected top-level keys {extra} alongside "
                f"{key!r} — misindented section?")
        doc = doc[key]
    return node_options_from_dict(doc)
