"""Nemesis: a composable fault scheduler for chaos drives.

The reference validates its cluster behavior with ad-hoc kill/restart
loops in tests (SURVEY.md §5); tpuraft packages the pattern: a nemesis
repeatedly picks a fault from a weighted menu, applies it, dwells,
heals, and records a timeline.  Faults are plain async callables, so
the same schedule drives any fabric — the in-proc loopback network,
`FaultInjectingTransport`-wrapped real sockets, or process kills.

Usage::

    actions = [
        NemesisAction("drop+delay", apply=start_noise, heal=stop_noise,
                      dwell_s=0.8),
        NemesisAction("leader-kill", apply=kill_leader, heal=restart,
                      dwell_s=0.6, weight=2.0),
    ]
    timeline = await run_nemesis(actions, duration_s=60,
                                 rng=random.Random(7))
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

LOG = logging.getLogger(__name__)


@dataclass
class NemesisAction:
    name: str
    apply: Callable[[], Awaitable[None]]
    heal: Callable[[], Awaitable[None]]
    dwell_s: float = 0.5          # fault duration before healing
    weight: float = 1.0           # relative pick probability
    # faults that sometimes cannot fire (e.g. no current leader) may
    # raise SkipFault from apply; the nemesis just picks again
    applied: int = field(default=0, compare=False)
    # optional post-heal invariant probe (crash-recovery actions assert
    # their recovery invariants here); a failure ABORTS the drive —
    # unlike apply/heal errors, a violated invariant is the verdict,
    # not noise to ride through
    check: Optional[Callable[[], Awaitable[None]]] = None


class SkipFault(Exception):
    """Raised by an action's apply() when the fault is not currently
    applicable (e.g. no leader to kill); the nemesis moves on."""


class StageTrap:
    """Membership-churn coordination: land a seeded crash INSIDE a
    specific ``_ConfigurationCtx`` stage (catching_up / joint / stable).

    Install :meth:`listener` as ``Node.conf_stage_listener`` on every
    node; a nemesis action then ``arm()``s the trap for a target stage
    and awaits :meth:`wait` — the moment any node's conf-change machine
    enters that stage, the trap records the node and fires, and the
    action kills the recorded node's store while the change is mid-stage.
    One-shot per arm(); disarmed while no action is waiting so steady-
    state churn costs nothing.
    """

    def __init__(self) -> None:
        self._armed: Optional[str] = None
        self._event = asyncio.Event()
        self.node = None   # the Node whose ctx hit the armed stage

    def listener(self, node, stage: str) -> None:
        """Install as ``node.conf_stage_listener`` (sync, called under
        the node lock — record and signal only)."""
        if self._armed == stage and not self._event.is_set():
            self.node = node
            self._event.set()

    def arm(self, stage: str) -> None:
        self._armed = stage
        self.node = None
        self._event = asyncio.Event()

    def disarm(self) -> None:
        self._armed = None

    async def wait(self, timeout_s: float) -> bool:
        """True when the armed stage was entered within ``timeout_s``
        (``self.node`` holds the node that entered it)."""
        try:
            await asyncio.wait_for(self._event.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            return False


async def run_nemesis(actions: list[NemesisAction], duration_s: float,
                      rng, pause_s: float = 0.3,
                      on_tick: Optional[Callable[[str], None]] = None
                      ) -> list[tuple[float, str]]:
    """Drive the fault schedule for ``duration_s``; returns the
    timeline [(t_offset, action_name), ...].  Every applied fault is
    healed before the next one fires (single-fault-at-a-time keeps
    drives reproducible and diagnosable)."""
    if not actions:
        raise ValueError("no nemesis actions")
    weights = [a.weight for a in actions]
    timeline: list[tuple[float, str]] = []
    t0 = time.monotonic()
    while time.monotonic() - t0 < duration_s:
        action = rng.choices(actions, weights=weights)[0]
        stamp = round(time.monotonic() - t0, 2)
        try:
            await action.apply()
        except SkipFault:
            await asyncio.sleep(pause_s)
            continue
        except Exception:
            LOG.exception("nemesis action %s failed to apply", action.name)
            try:
                # apply may have PARTIALLY taken effect before raising —
                # heal best-effort so a botched fault can't linger
                await action.heal()
            except Exception:
                LOG.exception("nemesis action %s failed to heal after "
                              "apply error", action.name)
            # the invariant probe runs on THIS path too: a recovery
            # failure the best-effort heal just swallowed must still
            # abort the drive, not hide in a log line
            if action.check is not None:
                await action.check()
            await asyncio.sleep(pause_s)
            continue
        action.applied += 1
        timeline.append((stamp, action.name))
        if on_tick:
            on_tick(action.name)
        try:
            await asyncio.sleep(action.dwell_s)
        finally:
            try:
                await action.heal()
            except Exception:
                LOG.exception("nemesis action %s failed to heal",
                              action.name)
        if action.check is not None:
            await action.check()   # invariant violation aborts the drive
        await asyncio.sleep(pause_s)
    return timeline
