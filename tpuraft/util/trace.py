"""Trace plane: per-op spans + the protocol flight recorder.

Two observability primitives the rest of the system feeds:

**Tracer** — a cheap per-op span recorder for the serving hot path.
Every stage the benches used to probe externally (client queue →
``_StoreSender`` batch → ``kv_command_batch`` RPC → server validate →
propose → log flush → quorum ack → FSM apply → client ack) emits a span
when tracing is enabled; disabled, every call site costs ONE attribute
branch (``if _TRACE.enabled``) — the zero-cost claim ``make bench-gate``
enforces.  Retention is two-tier: a seeded probabilistic sample keeps a
deterministic fraction of ops end to end (full stage spans, context on
the wire), and an adaptive slow-op trigger force-retains any op slower
than a rolling p99 EMA even when the sampler skipped it — root span
with duration and a ``slow`` flag, because the tail is exactly what
you want attributed but universal candidacy must cost one clock read
per op, not a span pipeline (``make bench-gate``'s 5% sampled-tracing
budget is the contract).
Spans live in a bounded ring and export as Chrome trace-event JSON
(``chrome://tracing`` / perfetto-loadable) via bench/soak ``--trace``.

A trace context (one i64: ``seq << 1 | sampled``) rides the KV batch
item and the ``AppendEntriesRequest`` as TRAILING defaulted wire fields
— old decoders stop before them — so follower-side append/flush spans
join the same trace across processes.  A remote process records a
context-carrying span only when the sampled bit is set (the slow-op
trigger is a client-local decision; its staging buffer cannot span
processes).

**FlightRecorder** — a per-process bounded ring of protocol events
(elections, term changes, conf-change stage transitions, quiesce/wake,
leadership evacuations, health transitions, fence-round failures, shed
bounces) that is ALWAYS on: appends are O(1) into a deque and the rare
events it records are exactly the ones you need after an incident.
``describe()`` renders the tail for SIGUSR2 dumps (util/describer);
``note_anomaly`` snapshots the ring on a detected anomaly (SICK
transition, election storm, soak oracle failure) so the state *leading
up to* the incident survives ring churn.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from typing import Optional

from tpuraft.util import describer

# perf_counter is the span clock (monotonic, ns resolution); one wall
# anchor taken at configure() maps it to absolute µs for the export
_pc = time.perf_counter


# graftcheck: loop-confined — created and consumed only by the Tracer
# (itself loop-confined below); executor threads never hold one
class _Staged:
    """One locally-originated op, staged until end_op decides retention
    (sampled => always; slow => force-retained).  Only SAMPLED ops
    buffer child spans — an unsampled op is duration-only (``spans``
    stays None), so the universal slow-op candidacy costs one clock
    read and two dict ops per op, not a span pipeline (the overhead
    gate's 5% budget is the contract)."""

    __slots__ = ("name", "proc", "t0", "sampled", "spans")

    def __init__(self, name: str, proc: str, t0: float, sampled: bool):
        self.name = name
        self.proc = proc
        self.t0 = t0
        self.sampled = sampled
        self.spans: Optional[list] = [] if sampled else None


# graftcheck: loop-confined — begin_op/end_op/span all run on the
# owning process's event loop (executor threads measure t0/t1 but the
# record call happens after the await returns); the ring deque is
# additionally safe for the exposition thread's len()/iteration
class Tracer:
    """Bounded-ring span recorder with seeded sampling + slow-op
    force-retention.  One module-level instance per process
    (:data:`TRACER`); components tag spans with their own ``proc``
    identity so an in-proc multi-store bench still attributes stages to
    client / leader store / follower store."""

    def __init__(self) -> None:
        self.enabled = False
        self.sample_rate = 0.01
        self._rng = random.Random(0)
        self._ring: deque = deque(maxlen=4096)
        self._staged: dict[int, _Staged] = {}
        self._max_staged = 1024
        self._next_seq = 1
        self._wall0 = time.time()
        self._pc0 = _pc()
        # adaptive slow-op trigger: asymmetric EMA tracking ~p99 of op
        # durations; an op above the estimate is retained even when the
        # sampler skipped it.  Warmup gate: the estimate means nothing
        # until it has seen a population.
        self.slow_trigger = True
        self._p99_ema = 0.0
        self._q_alpha = 0.05
        self._durs_seen = 0
        self._warmup = 100
        # counters (exposition / tests)
        self.ops_seen = 0
        self.ops_sampled = 0
        self.ops_slow_retained = 0
        self.ops_dropped = 0
        self.spans_recorded = 0

    # -- lifecycle -----------------------------------------------------------

    def configure(self, enabled: bool = True, sample_rate: float = 0.01,
                  seed: int = 0, ring: int = 4096,
                  slow_trigger: bool = True) -> "Tracer":
        """(Re)arm the tracer.  Seeded: two tracers configured alike
        sample the same op sequence — bench A/B runs compare like for
        like."""
        self.enabled = enabled
        self.sample_rate = sample_rate
        self._rng = random.Random(seed)
        if ring != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=ring)
        self.slow_trigger = slow_trigger
        # NOTE: the wall/perf anchor is NOT re-taken here — spans store
        # offsets relative to the anchor, so re-anchoring mid-process
        # would shift every already-recorded span in the export
        return self

    def reset(self) -> None:
        """Drop all recorded/staged spans and counters (test isolation)."""
        self._ring.clear()
        self._staged.clear()
        self._wall0 = time.time()
        self._pc0 = _pc()
        self._p99_ema = 0.0
        self._durs_seen = 0
        self.ops_seen = self.ops_sampled = 0
        self.ops_slow_retained = self.ops_dropped = 0
        self.spans_recorded = 0

    # -- op lifecycle (locally-originated traces) ----------------------------

    def begin_op(self, name: str = "op", proc: str = "client") -> int:
        """Open one op's trace; returns its context (0 = not traced —
        tracing disabled, or the staging buffer is full and the sampler
        skipped it).  The context's low bit is the sampled flag remote
        processes key retention on."""
        if not self.enabled:
            return 0
        self.ops_seen += 1
        sampled = self._rng.random() < self.sample_rate
        if not sampled and (not self.slow_trigger
                            or len(self._staged) >= self._max_staged):
            return 0
        tid = (self._next_seq << 1) | (1 if sampled else 0)
        self._next_seq += 1
        if sampled:
            self.ops_sampled += 1
        self._staged[tid] = _Staged(name, proc, _pc(), sampled)
        while len(self._staged) > self._max_staged:
            # evict the oldest abandoned op (an end_op that never came)
            self._staged.pop(next(iter(self._staged)))
        return tid

    def end_op(self, tid: int, **args) -> float:
        """Close an op: emit its root span and decide retention.
        Returns the op duration in seconds (0.0 if untraced)."""
        if not tid:
            return 0.0
        st = self._staged.pop(tid, None)
        if st is None:
            return 0.0
        t1 = _pc()
        dur = t1 - st.t0
        slow = self._note_dur(dur)
        if st.sampled or slow:
            if slow and not st.sampled:
                # force-retained by the slow trigger: the root span
                # (with duration + slow flag) is what survives — child
                # attribution exists only for sampled ops
                self.ops_slow_retained += 1
                args = dict(args, slow=True)
            self._emit(tid, st.name, st.proc, st.t0, t1, args)
            for span in st.spans or ():
                self._ring.append(span)
                self.spans_recorded += 1
        else:
            self.ops_dropped += 1
        return dur

    def span(self, tid: int, name: str, t0: float, t1: float,
             proc: str = "", **args) -> None:
        """Record one stage span of trace ``tid`` covering perf_counter
        interval [t0, t1].  Locally-staged traces buffer (retention
        decided at end_op); a remote context records iff sampled."""
        if not tid:
            return
        st = self._staged.get(tid)
        if st is not None:
            if st.spans is not None:
                st.spans.append(self._event(tid, name, proc or st.proc,
                                            t0, t1, args))
        elif tid & 1:
            self._emit(tid, name, proc or "remote", t0, t1, args)

    # -- internals -----------------------------------------------------------

    def _note_dur(self, dur: float) -> bool:
        """Feed the rolling p99 estimate; True = this op is slow (above
        the warmed estimate)."""
        self._durs_seen += 1
        if self._p99_ema == 0.0:
            self._p99_ema = dur
            return False
        slow = (self.slow_trigger and self._durs_seen > self._warmup
                and dur > self._p99_ema)
        # asymmetric quantile EMA: rise on the 1% above, fall 99x slower
        # on the mass below — settles near the p99 of the stream
        if dur > self._p99_ema:
            self._p99_ema += self._q_alpha * (dur - self._p99_ema)
        else:
            self._p99_ema -= (self._q_alpha / 99.0) * (self._p99_ema - dur)
        return slow

    def _event(self, tid: int, name: str, proc: str, t0: float, t1: float,
               args: dict) -> tuple:
        return (tid, name, proc, t0 - self._pc0, max(0.0, t1 - t0),
                args or None)

    def _emit(self, tid: int, name: str, proc: str, t0: float, t1: float,
              args: dict) -> None:
        self._ring.append(self._event(tid, name, proc, t0, t1, args))
        self.spans_recorded += 1

    # -- export / introspection ---------------------------------------------

    def spans(self, tid: Optional[int] = None) -> list[dict]:
        """Retained spans as dicts (newest last); optionally one trace's."""
        out = []
        for ev_tid, name, proc, rel0, dur, args in list(self._ring):
            if tid is not None and ev_tid != tid:
                continue
            out.append({"trace_id": ev_tid, "seq": ev_tid >> 1,
                        "name": name, "proc": proc,
                        "ts_s": rel0, "dur_s": dur,
                        "args": dict(args) if args else {}})
        return out

    def chrome_events(self) -> list[dict]:
        """Chrome trace-event ("X" complete events + process_name
        metadata) — the format chrome://tracing and perfetto load."""
        pids: dict[str, int] = {}
        events: list[dict] = []
        for ev_tid, name, proc, rel0, dur, args in list(self._ring):
            pid = pids.get(proc)
            if pid is None:
                pid = pids[proc] = len(pids) + 1
                events.append({"ph": "M", "name": "process_name",
                               "pid": pid, "tid": 0,
                               "args": {"name": proc}})
            ev = {"ph": "X", "name": name, "pid": pid,
                  "tid": ev_tid >> 1,
                  "ts": round((self._wall0 + rel0) * 1e6, 3),
                  "dur": round(dur * 1e6, 3),
                  "args": {"trace_id": ev_tid, **(args or {})}}
            events.append(ev)
        return events

    def export_chrome(self, path: str) -> int:
        """Write the ring as a perfetto-loadable JSON file; returns the
        number of span events written."""
        events = self.chrome_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        return sum(1 for e in events if e["ph"] == "X")

    def counters(self) -> dict:
        """Monotonic series only (Prometheus 'counter' semantics —
        rate()/increase() must never see a decrease)."""
        return {
            "trace_ops_seen": self.ops_seen,
            "trace_ops_sampled": self.ops_sampled,
            "trace_ops_slow_retained": self.ops_slow_retained,
            "trace_ops_dropped": self.ops_dropped,
            "trace_spans_recorded": self.spans_recorded,
        }

    def gauges(self) -> dict:
        """Point-in-time series (toggles, ring occupancy, EMAs)."""
        return {
            "trace_enabled": int(self.enabled),
            "trace_ring_spans": len(self._ring),
            "trace_slow_ema_ms": round(self._p99_ema * 1000.0, 3),
        }

    def stats(self) -> dict:
        """Everything, merged — the bench/soak report blob."""
        return {**self.counters(), **self.gauges()}

    def describe(self) -> str:
        c = self.stats()
        return (f"Tracer<enabled={self.enabled} rate={self.sample_rate} "
                f"ops={c['trace_ops_seen']} sampled={c['trace_ops_sampled']} "
                f"slow_retained={c['trace_ops_slow_retained']} "
                f"ring={c['trace_ring_spans']} "
                f"p99_ema={c['trace_slow_ema_ms']}ms>")


# -- trace-context wire helpers ----------------------------------------------
# One i64 per item/entry, little-endian, concatenated; b"" = untraced.
# Riding TRAILING defaulted wire fields keeps old decoders compatible
# (they stop before the field) and costs zero bytes when tracing is off.

import struct as _struct

_CTX = _struct.Struct("<q")


def store_proc(server_id) -> str:
    """The canonical span 'proc' identity for a store-side component.
    ONE derivation: cross-stage correlation (and the bench's
    leader-proc matching) requires every stage of one store to render
    the identical string — four call sites re-deriving it from
    slightly different server_id sources would silently split a
    store's spans across two 'processes' in the export."""
    return f"store:{server_id}"


def wire_ctx(tid: int) -> int:
    """The context an op PROPAGATES downstream: sampled ops carry their
    tid (full stage attribution), unsampled slow-candidates carry 0 —
    their only artifact is the client-side root span, so the serving
    path stays untouched for the 1-sample_rate majority."""
    return tid if tid & 1 else 0


def pack_ctx(tids: list[int]) -> bytes:
    """Pack per-item trace contexts; all-zero packs to b"" (no wire
    cost on the untraced path)."""
    if not any(tids):
        return b""
    return b"".join(_CTX.pack(t) for t in tids)


def unpack_ctx(blob: bytes, n: int) -> list[int]:
    """Unpack ``n`` per-item contexts; a missing/short blob (old sender,
    tracing off) yields zeros for every item."""
    if not blob or len(blob) < n * _CTX.size:
        return [0] * n
    return [_CTX.unpack_from(blob, i * _CTX.size)[0] for i in range(n)]


def entry_ctx(entries) -> bytes:
    """Pack the trace contexts of a log-entry batch for the
    AppendEntriesRequest trailing field."""
    return pack_ctx([e.trace_id for e in entries])


def adopt_entry_ctx(entries, blob: bytes) -> None:
    """Follower side: stamp wire-borne contexts onto decoded entries so
    their append/flush spans join the originating trace."""
    if not blob:
        return
    tids = unpack_ctx(blob, len(entries))
    for e, tid in zip(entries, tids):
        if tid:
            e.trace_id = tid


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Per-process bounded ring of protocol events + anomaly snapshots.

    Always on: the events it records (elections, conf-change stages,
    health transitions, evacuations, quiesce/wake, fence failures, shed
    bounces) happen at incident rate, not op rate, and a deque append
    is cheap enough to never gate.  Thread-safe: health transitions can
    arrive from the store's health task while node events arrive from
    RPC handlers on the same loop, and SIGUSR2 dumps from the signal
    frame.
    """

    def __init__(self, capacity: int = 2048) -> None:
        self._lock = threading.Lock()
        # writes serialized under _lock; reads are DELIBERATELY lock-free
        # (GIL-atomic deque snapshots via _snapshot) so dump()/describe()
        # stay safe from a SIGUSR2 frame that interrupted a record() call
        # holding the lock on this very thread
        self._ring: deque = deque(maxlen=capacity)  # guarded-by: _lock (writes)
        # anomaly snapshots: the ring tail AT the moment the anomaly
        # fired (ring churn after the incident must not erase the lead-up)
        self.anomalies: deque = deque(maxlen=8)     # guarded-by: _lock (writes)
        # election-storm detection: recent election_start timestamps per
        # group, pruned to the window
        self._elections: dict[str, deque] = {}      # guarded-by: _lock
        self._storm_last: dict[str, float] = {}     # guarded-by: _lock
        # coalescing windows for flood-prone event kinds (shed bounces
        # at request rate, mass hibernation sweeps), keyed per
        # (kind, group) so one store's flood can't swallow another's
        # first event or claim its suppressed count in the dump:
        # (kind, group) -> [window_start_monotonic, suppressed_count]
        self._coalesce: dict[tuple, list] = {}      # guarded-by: _lock
        self.storm_threshold = 5      # elections ...
        self.storm_window_s = 10.0    # ... within this window = a storm
        self.events_recorded = 0

    def record(self, kind: str, group: str = "", **detail) -> None:
        now = time.time()
        with self._lock:
            self._ring.append((now, kind, group, detail))
            self.events_recorded += 1
            if kind == "election_start" and group:
                self._note_election_locked(group, now)

    def _note_election_locked(self, group: str, now: float) -> None:
        dq = self._elections.get(group)
        if dq is None:
            dq = self._elections[group] = deque(maxlen=32)
            # bound the per-group map itself (region churn)
            if len(self._elections) > 512:
                self._elections.pop(next(iter(self._elections)))
        dq.append(now)
        while dq and now - dq[0] > self.storm_window_s:
            dq.popleft()
        if len(dq) >= self.storm_threshold:
            # once per window per group — a storm must not flood the
            # anomaly buffer with one snapshot per extra election
            if now - self._storm_last.get(group, 0.0) > self.storm_window_s:
                self._storm_last[group] = now
                self._anomaly_locked(
                    "election_storm",
                    f"group {group}: {len(dq)} elections in "
                    f"{self.storm_window_s:.0f}s")

    def record_coalesced(self, kind: str, group: str = "",
                         window_s: float = 1.0, per_group: bool = True,
                         **detail) -> None:
        """Leading-edge rate-bounded record for event kinds that can
        arrive in floods (a SICK store shedding at request rate, a
        hibernation sweep quiescing thousands of groups): the first
        occurrence in a window records immediately with its detail,
        the rest just count — the next recorded event of the kind
        carries ``suppressed=N`` plus ``suppressed_prior_s`` (how far
        back that suppressed window started), so a long-past flood's
        count reads as history, not as part of the new event.  Without
        coalescing, one incident's identical rows would evict the
        ring's entire lead-up (the exact history the recorder exists
        to keep).

        Windows are per (kind, group) by default — one source's flood
        must not swallow another's first event or claim its suppressed
        count in the dump.  Kinds whose flood IS many distinct groups
        at once (a hibernation sweep: every group quiesces exactly
        once, so each per-group window would be a leading edge and the
        sweep floods anyway) pass ``per_group=False`` to share one
        window per kind; the suppressed count then aggregates across
        groups and the recorded row's group is just the window's first
        trigger."""
        now = time.monotonic()
        key = (kind, group if per_group else "")
        with self._lock:
            ent = self._coalesce.get(key)
            if ent is not None and now - ent[0] < window_s:
                ent[1] += 1
                return
            if ent is not None and ent[1]:
                # time-stamp the carried count against ITS window — an
                # unrelated event hours later must not read as a flood
                detail = dict(detail, suppressed=ent[1],
                              suppressed_prior_s=round(now - ent[0], 1))
            if len(self._coalesce) > 1024:
                # bound the (kind, group) map itself (region churn)
                self._coalesce.pop(next(iter(self._coalesce)))
            self._coalesce[key] = [now, 0]
            self._ring.append((time.time(), kind, group, detail))
            self.events_recorded += 1

    def note_anomaly(self, reason: str, detail: str = "") -> None:
        """Snapshot the ring: something is wrong (SICK transition, soak
        oracle failure) and the lead-up events must survive churn."""
        with self._lock:
            self._anomaly_locked(reason, detail)

    def _anomaly_locked(self, reason: str, detail: str) -> None:
        # snapshot RAW tuples only — rendering 128 formatted lines here
        # would stall the event loop under the lock at the exact moment
        # (an election storm) the recorder is busiest; strings are built
        # lazily at dump/anomaly_report time
        self._ring.append((time.time(), "anomaly", "",
                           {"reason": reason, "detail": detail}))
        self.anomalies.append({
            "ts": time.time(),
            "reason": reason,
            "detail": detail,
            "raw_events": list(self._ring)[-128:],
        })

    def _snapshot(self, src) -> list:
        """LOCK-FREE read of a deque: dump()/describe() must be safe
        from a SIGNAL frame that may have interrupted a record() call
        holding ``_lock`` on this very thread — taking the lock there
        self-deadlocks the process.  ``list(deque)`` is GIL-safe except
        for a concurrent-mutation RuntimeError; retry, degrade to
        empty (a best-effort dump beats a hung node)."""
        for _ in range(4):
            try:
                return list(src)
            except RuntimeError:
                continue
        return []

    def events(self, last: int = 0) -> list[tuple]:
        evs = self._snapshot(self._ring)
        return evs[-last:] if last else evs

    @staticmethod
    def _render(evs: list) -> list[str]:
        out = []
        for ts, kind, group, detail in evs:
            stamp = time.strftime("%H:%M:%S", time.localtime(ts))
            extra = " ".join(f"{k}={v}" for k, v in detail.items())
            out.append(f"{stamp}.{int(ts % 1 * 1000):03d} {kind:<16} "
                       f"{group or '-':<24} {extra}".rstrip())
        return out

    def dump(self, last: int = 256) -> str:
        """Structured text dump of the event tail (SIGUSR2 / soak
        failure attachment).  Lock-free: callable from a signal frame."""
        lines = self._render(self.events(last))
        hdr = (f"--- flight recorder: {len(lines)} recent events, "
               f"{self.events_recorded} total, "
               f"{len(self.anomalies)} anomalies ---")
        return "\n".join([hdr] + lines)

    def anomaly_report(self) -> list[dict]:
        """Anomaly snapshots for machine-readable attachment (the
        soak's failure report); raw tuples render here, off the
        recording path."""
        return [{"ts": a["ts"], "reason": a["reason"],
                 "detail": a["detail"],
                 "events": self._render(a["raw_events"])}
                for a in self._snapshot(self.anomalies)]

    def counters(self) -> dict:
        """Monotonic series (Prometheus counter semantics); lock-free
        int/len reads (the exposition thread must never contend the
        recording path)."""
        return {"recorder_events": self.events_recorded}

    def gauges(self) -> dict:
        return {
            "recorder_ring": len(self._ring),
            "recorder_anomalies": len(self.anomalies),
        }

    def stats(self) -> dict:
        return {**self.counters(), **self.gauges()}

    def describe(self) -> str:
        return self.dump(last=64)


# Module-level singletons: one tracer + one recorder per process.  All
# components record into these; the describer renders them on SIGUSR2.
TRACER = Tracer()
RECORDER = FlightRecorder()
describer.register(TRACER)
describer.register(RECORDER)
