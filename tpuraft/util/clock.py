"""Injectable time plane: per-store clocks, seeded clock faults, and
the peer-skew sentinel (ISSUE 18).

Every timing-sensitive consumer in the consensus path (election timers,
leader-lease math, store-lease bookkeeping, engine tick deadlines,
health hysteresis) reads time through a :class:`Clock` handle instead of
calling ``time.monotonic()`` directly.  The default is :data:`SYSTEM` —
two staticmethods bound straight to the C-level ``time`` functions, so
an uninstalled clock costs one attribute load over the raw call (the
``kv_ops_clocked`` bench-gate row holds that at <=2%).  A soak installs
a :class:`ChaosClock` per store and the whole store — timers, leases,
hibernation — experiences drift, forward jumps, and freezes coherently,
exactly like a machine with a broken TSC or a VM pausing under
migration.

Safety story (docs/architecture.md "Lease safety under bounded drift"):
LEASE_BASED reads and store-liveness leases compare durations measured
on TWO different clocks.  ``RaftOptions.clock_drift_bound`` (rho)
shrinks every lease the holder trusts by (1 - rho) and is the bound the
deployment promises; the :class:`ClockSentinel` is the detector for the
promise being BROKEN — it estimates each peer's clock rate from beat
acks and, when the median peer disagrees with the local clock by more
than rho, fails lease checks closed so reads fall back to the SAFE
quorum path (linearizable with no clock trust at all).
"""

from __future__ import annotations

import random
import time
from typing import Optional


class SystemClock:
    """Real time.  ``monotonic``/``wall`` are staticmethods bound to the
    C accelerators — calling through an instance adds one attribute
    lookup over the bare call, which is the whole indirection cost."""

    monotonic = staticmethod(time.monotonic)
    wall = staticmethod(time.time)

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return "SystemClock()"


#: module default: what every consumer falls back to when no clock is
#: injected.  A module-level singleton (not per-consumer construction)
#: so identity checks like ``clock is SYSTEM`` stay meaningful in tests.
SYSTEM = SystemClock()


def resolve(clock: Optional[object]):
    """``opts.clock or SYSTEM`` with a home: the one-line idiom every
    constructor uses, kept here so the default has a single owner."""
    return clock if clock is not None else SYSTEM


class ChaosClock:
    """A monotonic+wall clock with injectable rate drift, forward
    jumps, and freezes — the fault model for ISSUE 18's time plane.

    The virtual clock is piecewise-linear over the base clock:
    ``monotonic() = anchor_virt + (base - anchor_real) * rate``.  Every
    mutation (``set_rate``/``jump``/``freeze``/``unfreeze``) re-anchors
    at the current instant, so the virtual timeline is continuous
    (except across ``jump``, which is the point) and NEVER runs
    backwards — a frozen clock holds still, a 1.1x clock runs fast from
    here on.  ``wall()`` mirrors the same virtual timeline offset onto
    the base wall clock, so wall-stamped logs skew coherently.

    Deterministic given the event sequence; the ``rng`` only feeds
    :meth:`chaos_step` (the soak's seeded per-store fault driver).
    """

    def __init__(self, seed: int = 0, base: Optional[object] = None):
        self._base = resolve(base)
        self._anchor_real = self._base.monotonic()
        self._anchor_virt = self._anchor_real
        self._rate = 1.0
        self._rate_before_freeze = 1.0
        self.rng = random.Random(seed)
        # injection counters for soak/run reports
        self.faults: dict[str, int] = {
            "drift": 0, "jump": 0, "freeze": 0, "unfreeze": 0}

    # -- reads ---------------------------------------------------------------

    def monotonic(self) -> float:
        return self._anchor_virt \
            + (self._base.monotonic() - self._anchor_real) * self._rate

    def wall(self) -> float:
        # the wall clock carries the same virtual-vs-real displacement
        return self._base.wall() + (self.monotonic()
                                    - self._base.monotonic())

    # -- fault injection -----------------------------------------------------

    def _rebase(self) -> None:
        now_real = self._base.monotonic()
        self._anchor_virt = self._anchor_virt \
            + (now_real - self._anchor_real) * self._rate
        self._anchor_real = now_real

    def set_rate(self, rate: float) -> None:
        """Run ``rate`` virtual seconds per real second from now on
        (1.1 = 10% fast, 0.9 = 10% slow, 0 = frozen)."""
        if rate < 0.0:
            raise ValueError("a monotonic clock cannot run backwards")
        self._rebase()
        self._rate = rate
        if rate != 1.0:
            self.faults["drift"] += 1

    def jump(self, seconds: float) -> None:
        """Step the clock FORWARD by ``seconds`` instantly (leap
        second, NTP slam, VM resume)."""
        if seconds < 0.0:
            raise ValueError("a monotonic clock cannot jump backwards")
        self._rebase()
        self._anchor_virt += seconds
        self.faults["jump"] += 1

    def freeze(self) -> None:
        """Hold the clock still until :meth:`unfreeze` (stuck counter,
        paused VM)."""
        if self._rate != 0.0:
            self._rate_before_freeze = self._rate
        self.set_rate(0.0)
        self.faults["freeze"] += 1

    def unfreeze(self) -> None:
        self.set_rate(self._rate_before_freeze)
        self.faults["unfreeze"] += 1

    @property
    def rate(self) -> float:
        return self._rate

    @property
    def frozen(self) -> bool:
        return self._rate == 0.0

    def heal(self) -> None:
        """Back to real rate (accumulated offset persists — healing a
        drifted clock does not step it backwards)."""
        self.set_rate(1.0)

    def chaos_step(self) -> str:
        """One seeded fault from the soak menu: drift fast/slow, jump
        forward, or freeze; a frozen clock always unfreezes first so
        faults keep composing.  Returns a description for the log."""
        if self.frozen:
            self.unfreeze()
            return "unfreeze"
        roll = self.rng.random()
        if roll < 0.4:
            rate = self.rng.choice([1.05, 1.1, 1.25, 0.9, 0.8])
            self.set_rate(rate)
            return f"drift rate={rate}"
        if roll < 0.75:
            s = 0.2 + self.rng.random() * 1.3
            self.jump(s)
            return f"jump +{s:.2f}s"
        self.freeze()
        return "freeze"

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return (f"ChaosClock(rate={self._rate}, "
                f"virt={self.monotonic():.3f})")


class ClockSentinel:
    """Peer-skew estimator riding the beat RTT probes (ISSUE 18
    DETECTION).

    Beat acks carry the responder's clock reading (``clock_ms``); the
    hub feeds each (send instant, ack instant, peer reading) triple
    here.  Per peer we track the peer-vs-local clock-RATE ratio over
    successive acks — ``(peer_now - peer_prev) / (local_now -
    local_prev)`` EWMA-smoothed — and the peer-vs-local offset.  All
    arithmetic runs on the LOCAL injected clock: a frozen local clock
    makes every peer look infinitely fast, a 1.1x local clock makes
    every peer look ~0.91x slow, which is exactly the symmetry the
    median vote exploits: when the MEDIAN peer deviates beyond the
    drift bound, the local clock is the suspect (a minority of broken
    peers cannot outvote the majority), and lease checks fail closed.

    ``suspect()`` is the one consumer-facing bit: True means "do not
    trust a lease on this store's clock".  Recovery is automatic — the
    estimate re-converges once the clock heals (EWMA horizon), so a
    transient jump fences reads only for a few beat rounds.
    """

    #: ignore rate samples over windows shorter than this — RTT jitter
    #: swamps the numerator below it
    MIN_WINDOW_S = 0.05
    #: EWMA weight for new rate samples (≈10-sample horizon)
    ALPHA = 0.2
    #: offset step (seconds) flagged as a jump anomaly even when the
    #: rate estimate has not yet crossed the bound
    JUMP_S = 0.25

    def __init__(self, drift_bound: float = 0.0,
                 clock: Optional[object] = None, label: str = ""):
        self._clock = resolve(clock)
        self.drift_bound = drift_bound
        self.label = label
        # peer -> (last local midpoint, last peer reading, rate EWMA)
        self._peers: dict[str, tuple[float, float, Optional[float]]] = {}
        self._offsets: dict[str, float] = {}
        self._suspect = False
        # counters (summed into store describe / soak reports)
        self.samples = 0
        self.anomalies = 0
        self.lease_fenced = 0      # lease checks failed closed by us
        self._last_reason = ""
        # per-peer gauges register lazily as peers first report — the
        # roster is not known at store boot (membership changes)
        self._metrics = None
        self._peer_gauges: set = set()

    # -- intake --------------------------------------------------------------

    def observe(self, peer: str, peer_clock_s: float,
                sent_at: float, acked_at: float) -> None:
        """One beat-ack probe: local send/ack instants (local clock)
        and the peer's clock reading taken while serving the ack."""
        if peer_clock_s <= 0.0:
            return            # peer predates the clock_ms field
        local_mid = (sent_at + acked_at) / 2.0
        prev = self._peers.get(peer)
        self._offsets[peer] = peer_clock_s - local_mid
        self._register_peer_gauge(peer)
        if prev is None:
            self._peers[peer] = (local_mid, peer_clock_s, None)
            return
        prev_mid, prev_peer, ewma = prev
        d_local = local_mid - prev_mid
        d_peer = peer_clock_s - prev_peer
        self.samples += 1
        if d_local < self.MIN_WINDOW_S:
            # local clock barely advanced between acks.  Real cadence
            # puts beats many MIN_WINDOW_S apart, so a near-zero local
            # delta while the peer advanced is the FROZEN-local-clock
            # signature — score it as an extreme ratio instead of
            # discarding it (discarding would blind the sentinel to
            # the one fault rate math cannot see).
            if d_peer > 10.0 * max(d_local, 1e-6):
                ratio = 100.0
            else:
                return
        else:
            ratio = d_peer / d_local
        ewma = ratio if ewma is None \
            else ewma + self.ALPHA * (ratio - ewma)
        self._peers[peer] = (local_mid, peer_clock_s, ewma)
        self._reassess()

    def forget(self, peer: str) -> None:
        self._peers.pop(peer, None)
        self._offsets.pop(peer, None)

    # -- assessment ----------------------------------------------------------

    def _median_ratio(self) -> Optional[float]:
        rates = sorted(e for _, _, e in self._peers.values()
                       if e is not None)
        if not rates:
            return None
        return rates[len(rates) // 2]

    def _reassess(self) -> None:
        if self.drift_bound <= 0.0:
            return            # detection-only deployment: never fence
        med = self._median_ratio()
        if med is None:
            return
        bad = abs(med - 1.0) > self.drift_bound
        if bad and not self._suspect:
            self._suspect = True
            self.anomalies += 1
            self._last_reason = f"median peer clock rate {med:.3f}"
            self._emit("suspect", med)
        elif not bad and self._suspect:
            self._suspect = False
            self._emit("cleared", med)

    def _emit(self, what: str, med: float) -> None:
        from tpuraft.util.trace import RECORDER

        RECORDER.record("clock_anomaly", group=self.label, state=what,
                        median_rate=round(med, 4),
                        bound=self.drift_bound)
        if what == "suspect":
            RECORDER.note_anomaly(
                "clock_anomaly",
                f"{self.label}: local clock suspect — {self._last_reason}"
                f" (bound {self.drift_bound})")

    # -- consumers -----------------------------------------------------------

    def suspect(self) -> bool:
        """True = the LOCAL clock disagrees with the peer median beyond
        the drift bound: lease math must not be trusted."""
        return self._suspect

    def lease_check(self) -> bool:
        """Gate a lease-validity check: False forces the caller onto
        the clock-independent path and counts the fence."""
        if self._suspect:
            self.lease_fenced += 1
            return False
        return True

    def skew_of(self, peer: str) -> Optional[float]:
        """Latest estimated peer-minus-local clock offset (seconds);
        None before the first probe."""
        return self._offsets.get(peer)

    def rate_of(self, peer: str) -> Optional[float]:
        e = self._peers.get(peer)
        return e[2] if e else None

    def peers(self) -> dict[str, dict]:
        out = {}
        for p, (_, _, ewma) in self._peers.items():
            out[p] = {
                "skew_s": round(self._offsets.get(p, 0.0), 4),
                "rate": round(ewma, 4) if ewma is not None else None,
            }
        return out

    def counters(self) -> dict[str, int]:
        return {
            "clock_skew_samples": self.samples,
            "clock_anomalies": self.anomalies,
            "clock_lease_fenced": self.lease_fenced,
            "clock_suspect": int(self._suspect),
        }

    def gauges(self) -> dict[str, float]:
        """Pull-style gauge dict for exposition paths that bypass the
        opt-in KV registry (StoreEngine.metrics_counters, the health /
        disk-budget pattern) — the ``admin.py clocks`` dashboard must
        work against a store that never enabled KV metrics."""
        out = {
            "clock.suspect": float(self._suspect),
            "clock.max_abs_skew_s": max(
                (abs(v) for v in self._offsets.values()), default=0.0),
            "clock.lease_fenced": float(self.lease_fenced),
        }
        for p, off in list(self._offsets.items()):
            out[f"clock.peer_skew_s.{p}"] = off
        return out

    def register_gauges(self, metrics) -> None:
        """Prometheus surface: suspect flag, worst |skew|, fence count,
        plus a per-peer skew gauge as each peer first reports (the
        ``admin.py clocks`` dashboard reads these)."""
        metrics.gauge("clock.suspect", lambda: float(self._suspect))
        metrics.gauge(
            "clock.max_abs_skew_s",
            lambda: max((abs(v) for v in self._offsets.values()),
                        default=0.0))
        metrics.gauge("clock.lease_fenced",
                      lambda: float(self.lease_fenced))
        self._metrics = metrics
        for p in list(self._peers):
            self._register_peer_gauge(p)

    def _register_peer_gauge(self, peer: str) -> None:
        if self._metrics is None or peer in self._peer_gauges:
            return
        self._peer_gauges.add(peer)
        self._metrics.gauge(
            f"clock.peer_skew_s.{peer}",
            lambda p=peer: self._offsets.get(p, 0.0))

    def snapshot(self) -> dict:
        """Structured view (admin RPC / soak report)."""
        med = self._median_ratio()
        return {
            "suspect": self._suspect,
            "drift_bound": self.drift_bound,
            "median_rate": round(med, 4) if med is not None else None,
            "peers": self.peers(),
            **self.counters(),
        }

    def describe(self) -> str:
        med = self._median_ratio()
        peers = ", ".join(
            f"{p}=skew{d['skew_s']:+.3f}s"
            + (f"@x{d['rate']}" if d["rate"] is not None else "")
            for p, d in sorted(self.peers().items())) or "-"
        return (f"ClockSentinel<{self.label or '-'} "
                f"suspect={self._suspect} bound={self.drift_bound} "
                f"median_rate={med if med is None else round(med, 4)} "
                f"samples={self.samples} anomalies={self.anomalies} "
                f"fenced={self.lease_fenced} peers=[{peers}]>")
