"""L1 runtime utilities (reference: core:util/ — SURVEY.md §3.1)."""

from tpuraft.util.timer import RepeatedTimer
from tpuraft.util.metrics import MetricRegistry
from tpuraft.util import describer

__all__ = ["RepeatedTimer", "MetricRegistry", "describer"]
