"""Metrics: counters, gauges, histograms/timers per node.

Reference parity: Dropwizard ``MetricRegistry`` via ``core:core/NodeMetrics``,
``ThreadPoolMetricSet``, ``DisruptorMetricSet`` (SURVEY.md §6).  Names keep
the reference's dotted style (``replicate-entries``, ``append-logs``...).
Lightweight by design: a disabled registry costs one branch.
"""

from __future__ import annotations

import bisect
import time
from collections import defaultdict
from typing import Callable, Optional


class Histogram:
    """Reservoir-free histogram: keeps a bounded ring of samples."""

    __slots__ = ("_samples", "_max", "count", "total")

    def __init__(self, max_samples: int = 4096):
        self._samples: list[float] = []
        self._max = max_samples
        self.count = 0
        self.total = 0.0

    def update(self, value: float) -> None:
        self.count += 1
        self.total += value
        if len(self._samples) >= self._max:
            self._samples[self.count % self._max] = value
        else:
            self._samples.append(value)

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, int(p / 100.0 * len(s)))
        return s[idx]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.percentile(50),
            "p99": self.percentile(99),
            "max": max(self._samples) if self._samples else 0.0,
        }


class MetricRegistry:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.counters: dict[str, int] = defaultdict(int)
        self.histograms: dict[str, Histogram] = {}
        self.gauges: dict[str, Callable[[], float]] = {}

    def counter(self, name: str, delta: int = 1) -> None:
        if self.enabled:
            self.counters[name] += delta

    def histogram(self, name: str) -> Optional[Histogram]:
        if not self.enabled:
            return None
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram()
        return h

    def update(self, name: str, value: float) -> None:
        if self.enabled:
            self.histogram(name).update(value)

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        if self.enabled:
            self.gauges[name] = fn

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def snapshot(self) -> dict:
        return {
            "counters": dict(self.counters),
            "histograms": {k: h.snapshot() for k, h in self.histograms.items()},
            "gauges": {k: g() for k, g in self.gauges.items()},
        }


class _Timer:
    """``with metrics.timer("replicate-entries"): ...`` records millis."""

    __slots__ = ("_reg", "_name", "_t0")

    def __init__(self, reg: MetricRegistry, name: str):
        self._reg = reg
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._reg.update(self._name, (time.perf_counter() - self._t0) * 1000.0)
        return False
