"""Metrics: counters, gauges, histograms/timers per node.

Reference parity: Dropwizard ``MetricRegistry`` via ``core:core/NodeMetrics``,
``ThreadPoolMetricSet``, ``DisruptorMetricSet`` (SURVEY.md §6).  Names keep
the reference's dotted style (``replicate-entries``, ``append-logs``...).
Lightweight by design: a disabled registry costs one branch.

Thread-safety: histogram samples arrive from executor threads (storage
flush timing) while the event loop reads percentiles and the metrics
HTTP listener renders snapshots — every read-modify-write here is
locked.  ``prometheus_text`` renders any counters/gauges/histograms
mapping in the Prometheus text exposition format (the live-scrape side
of the observability plane; see StoreEngine.metrics_text).
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import defaultdict
from typing import Callable, Optional


class Histogram:
    """Reservoir-free histogram: keeps a bounded ring of samples.

    The ring replaces OLDEST-first once full (a dedicated write cursor
    — deriving it from the post-increment ``count`` skewed slot 0 on
    the first wrap), and ``percentile`` serves from a cached sort that
    a dirty flag invalidates on update instead of re-sorting the whole
    ring per call.
    """

    __slots__ = ("_samples", "_max", "_next", "_sorted", "_dirty",
                 "_lock", "count", "total")

    def __init__(self, max_samples: int = 4096):
        self._samples: list[float] = []
        self._max = max_samples
        self._next = 0            # guarded-by: _lock — ring write cursor
        self._sorted: list[float] = []  # guarded-by: _lock — cached sort
        self._dirty = False       # guarded-by: _lock
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0

    def update(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.total += value
            if len(self._samples) >= self._max:
                self._samples[self._next] = value
                self._next = (self._next + 1) % self._max
            else:
                self._samples.append(value)
            self._dirty = True

    def _sorted_locked(self) -> list[float]:
        if self._dirty:
            self._sorted = sorted(self._samples)
            self._dirty = False
        return self._sorted

    def percentile(self, p: float) -> float:
        with self._lock:
            s = self._sorted_locked()
            if not s:
                return 0.0
            # nearest-rank: the smallest sample with at least p% of the
            # population at or below it — p99 of 100 samples is the
            # 99th value, p50 of 4 is the 2nd (int-floor indexing was
            # off by one toward the tail on small populations)
            idx = max(0, min(len(s) - 1,
                             math.ceil(p / 100.0 * len(s)) - 1))
            return s[idx]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            s = self._sorted_locked()
            count, total = self.count, self.total

            def pct(p: float) -> float:
                if not s:
                    return 0.0
                return s[max(0, min(len(s) - 1,
                                    math.ceil(p / 100.0 * len(s)) - 1))]

            return {
                "count": count,
                "mean": total / count if count else 0.0,
                "p50": pct(50),
                "p99": pct(99),
                "max": s[-1] if s else 0.0,
            }


class MetricRegistry:
    """Thread-safe: counter bumps and histogram creation arrive from
    executor threads while loop-side readers snapshot."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self.counters: dict[str, int] = defaultdict(int)
        self.histograms: dict[str, Histogram] = {}
        self.gauges: dict[str, Callable[[], float]] = {}

    def counter(self, name: str, delta: int = 1) -> None:
        if self.enabled:
            with self._lock:
                self.counters[name] += delta

    def histogram(self, name: str) -> Optional[Histogram]:
        if not self.enabled:
            return None
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.get(name)
                if h is None:
                    h = self.histograms[name] = Histogram()
        return h

    def update(self, name: str, value: float) -> None:
        if self.enabled:
            self.histogram(name).update(value)

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        if self.enabled:
            with self._lock:
                self.gauges[name] = fn

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def counters_snapshot(self) -> dict:
        """Locked copy of the counter map — cross-thread readers (the
        metrics HTTP daemon thread) must not iterate the live dict a
        first-seen ``count()`` on the loop can resize mid-scrape."""
        with self._lock:
            return dict(self.counters)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            hists = list(self.histograms.items())
            gauges = list(self.gauges.items())
        return {
            "counters": counters,
            "histograms": {k: h.snapshot() for k, h in hists},
            "gauges": {k: g() for k, g in gauges},
        }


class _Timer:
    """``with metrics.timer("replicate-entries"): ...`` records millis."""

    __slots__ = ("_reg", "_name", "_t0")

    def __init__(self, reg: MetricRegistry, name: str):
        self._reg = reg
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._reg.update(self._name, (time.perf_counter() - self._t0) * 1000.0)
        return False


# ---- Prometheus text exposition --------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str = "tpuraft_") -> str:
    n = _NAME_RE.sub("_", name)
    if not n.startswith(prefix):
        n = prefix + n
    return n


def _prom_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{_NAME_RE.sub("_", k)}="{str(v)}"' for k, v in labels.items())
    return "{" + body + "}"


def prometheus_text(counters: Optional[dict] = None,
                    gauges: Optional[dict] = None,
                    histograms: Optional[dict] = None,
                    labels: Optional[dict] = None) -> str:
    """Render flat metric mappings as Prometheus text format.

    ``counters``/``gauges`` map name -> number; ``histograms`` maps
    name -> a :meth:`Histogram.snapshot` dict (rendered as _count/_sum
    plus p50/p99/max quantile gauges).  ``labels`` (e.g. the store
    endpoint) are attached to every sample.
    """
    out: list[str] = []
    lbl = _prom_labels(labels)
    for name, value in sorted((counters or {}).items()):
        n = _prom_name(name)
        out.append(f"# TYPE {n} counter")
        out.append(f"{n}{lbl} {value}")
    for name, value in sorted((gauges or {}).items()):
        n = _prom_name(name)
        out.append(f"# TYPE {n} gauge")
        out.append(f"{n}{lbl} {value}")
    for name, snap in sorted((histograms or {}).items()):
        n = _prom_name(name)
        out.append(f"# TYPE {n} summary")
        for q, key in (("0.5", "p50"), ("0.99", "p99")):
            qlbl = _prom_labels(dict(labels or {}, quantile=q))
            out.append(f"{n}{qlbl} {snap.get(key, 0.0)}")
        out.append(f"{n}_count{lbl} {snap.get('count', 0)}")
        out.append(f"{n}_sum{lbl} "
                   f"{snap.get('mean', 0.0) * snap.get('count', 0)}")
        out.append(f"{n}_max{lbl} {snap.get('max', 0.0)}")
    return "\n".join(out) + ("\n" if out else "")
