"""Quorum-intersection math for membership changes.

Shared by the pytest membership oracle (tests/oracle.py) and the churn
soak's live invariant check (examples/soak.py) so the two can never
silently diverge on what counts as a violation.  Everything here is
verified BY ENUMERATION — exponential in voter-set size, fine for the
≤7-voter sets the chaos drives produce.
"""

from __future__ import annotations

from itertools import combinations
from typing import Iterable


def majorities(s: Iterable) -> list[frozenset]:
    """All minimal majorities (quorums) of voter set ``s``."""
    s = set(s)
    q = len(s) // 2 + 1
    return [frozenset(c) for c in combinations(sorted(s, key=str), q)]


def majorities_intersect(a: Iterable, b: Iterable) -> bool:
    """True iff EVERY majority of voter set ``a`` intersects EVERY
    majority of voter set ``b`` (the safety condition for two quorum
    systems to share decisions).

    Disjoint majorities exist iff each side can fill its quorum while
    ceding the shared members to the other: side a must take
    ``max(0, |Qa| - |a\\b|)`` members from the intersection, likewise b;
    if those demands fit inside ``|a ∩ b|`` together, disjoint quorums
    exist.
    """
    a, b = set(a), set(b)
    if not a or not b:
        return False
    qa, qb = len(a) // 2 + 1, len(b) // 2 + 1
    need_a = max(0, qa - len(a - b))
    need_b = max(0, qb - len(b - a))
    return need_a + need_b > len(a & b)


def witness_minority(voters: Iterable, witnesses: Iterable) -> bool:
    """Config rule for witness voters: witnesses must be a strict
    minority (< quorum) of the voter set, with at least one data voter.
    Guarantees — verified by :func:`every_majority_has_data_peer` —
    that EVERY majority contains at least one payload-holding replica,
    so no quorum can certify a commit that exists on zero data copies.
    """
    voters, witnesses = set(voters), set(witnesses)
    if not witnesses:
        return True
    if not witnesses <= voters or witnesses == voters:
        return False
    return len(witnesses) < len(voters) // 2 + 1


def every_majority_has_data_peer(voters: Iterable,
                                 witnesses: Iterable) -> bool:
    """Enumerate EVERY majority of ``voters`` and check each contains
    at least one non-witness (data) member — the witness-safety quorum
    condition (a majority made of witnesses alone could ack a commit
    held on zero data replicas)."""
    witnesses = set(witnesses)
    return all(m - witnesses for m in majorities(voters))


def witness_only_majorities(voters: Iterable,
                            witnesses: Iterable) -> list[frozenset]:
    """Majorities containing NO data replica — each is a quorum that
    must never certify a commit.  Two independent mechanisms enforce
    that: config validation (witness_minority makes this list empty for
    valid confs) and, defense in depth, witnesses never campaign — a
    commit quorum always contains the (data) leader, and the ballot box
    additionally clamps the commit point to the data replicas' best
    match (ballot_box.commit_point)."""
    witnesses = set(witnesses)
    return [m for m in majorities(voters) if not (m - witnesses)]


def joint_quorums_intersect(old: Iterable, new: Iterable) -> bool:
    """A joint (C_old,new) decision takes a majority of BOTH sets.
    Verify by enumeration that every such dual quorum intersects every
    majority of old, every majority of new, and every other dual quorum
    — the quorum-intersection invariant across a membership change."""
    old, new = set(old), set(new)
    if not old or not new:
        return False
    duals = [qo | qn for qo in majorities(old) for qn in majorities(new)]
    singles = majorities(old) + majorities(new)
    return (all(d & m for d in duals for m in singles)
            and all(d1 & d2 for d1 in duals for d2 in duals))
