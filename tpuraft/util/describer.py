"""Live-state dumps for running nodes.

Reference parity [1.3+]: ``Node#describe`` / ``Describer`` printer plus
``NodeDescribeSignalHandler`` / ``NodeMetricsSignalHandler`` (SIGUSR2
dumps — SURVEY.md §6 "Tracing / profiling").  Anything with a
``describe() -> str`` method can be registered; ``dump_all()`` renders
every live registrant, and ``install_signal_dump()`` wires that to a
signal for in-production inspection.
"""

from __future__ import annotations

import logging
import signal
import sys
import time
import weakref
from typing import Optional

LOG = logging.getLogger(__name__)


class DescriberRegistry:
    """Holds weak references so registration never delays GC of a node."""

    def __init__(self) -> None:
        self._objs: "weakref.WeakSet" = weakref.WeakSet()

    def register(self, obj) -> None:
        self._objs.add(obj)

    def unregister(self, obj) -> None:
        self._objs.discard(obj)

    def dump(self) -> str:
        parts = [f"--- describe @ {time.strftime('%Y-%m-%d %H:%M:%S')} "
                 f"({len(self._objs)} objects) ---"]
        for obj in sorted(self._objs, key=str):
            try:
                parts.append(obj.describe())
            except Exception as e:  # a dump must never take the process down
                parts.append(f"{obj}: describe failed: {e!r}")
        return "\n".join(parts)


_registry = DescriberRegistry()


def register(obj) -> None:
    _registry.register(obj)


def unregister(obj) -> None:
    _registry.unregister(obj)


def dump_all() -> str:
    return _registry.dump()


def install_signal_dump(signum: int = signal.SIGUSR2,
                        path: Optional[str] = None) -> None:
    """Dump all registered describers on ``signum`` (default SIGUSR2), to
    ``path`` (append) or stderr.  Safe to call more than once."""

    def _handler(_sig, _frame):
        text = dump_all()
        if path:
            with open(path, "a") as f:
                f.write(text + "\n")
        else:
            print(text, file=sys.stderr)

    signal.signal(signum, _handler)
