"""RepeatedTimer: stoppable, restartable recurring timer with jitter.

Reference parity: ``core:util/RepeatedTimer`` (election/vote/stepdown/
snapshot timers — SURVEY.md §3.1 "Timers & queues").  asyncio-native: one
task per timer instead of a hashed wheel; the multi-raft engine replaces
per-group timers with tick-tensor deadlines (tpuraft.ops.tick), so this
class only backs the single-group host runtime and the snapshot cadence.

Time discipline (ISSUE 18): the delay is a DEADLINE on the injected
clock, slept toward in bounded real-time slices — so a store whose
ChaosClock runs 1.1x fast fires its election timers early, a frozen
clock never fires them, and a forward jump fires them immediately,
exactly like a real machine with that clock.  With the default
SystemClock one slice covers the whole delay and the loop degenerates
to the single ``asyncio.sleep`` it always was.
"""

from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable, Optional

from tpuraft.util import clock as _clockmod


class RepeatedTimer:
    #: real-seconds cap per sleep slice under an injected clock: the
    #: lag bound between a clock fault landing and the timer noticing
    _SLICE_S = 0.05

    def __init__(
        self,
        name: str,
        timeout_ms: int,
        on_trigger: Callable[[], Awaitable[None]],
        adjust: Optional[Callable[[int], int]] = None,
        clock: Optional[object] = None,
    ):
        """``adjust`` maps the base timeout to the actual per-round delay —
        e.g. randomized election timeouts (reference: NodeImpl's
        ``randomTimeout``)."""
        self._name = name
        self._timeout_ms = timeout_ms
        self._on_trigger = on_trigger
        self._adjust = adjust or (lambda t: t)
        self._clock = _clockmod.resolve(clock)
        self._task: Optional[asyncio.Task] = None
        self._stopped = True
        self._destroyed = False

    @staticmethod
    def random_adjust(timeout_ms: int) -> int:
        """Election-style jitter: [timeout, 2*timeout)."""
        return timeout_ms + random.randrange(timeout_ms)

    def start(self) -> None:
        if self._destroyed or not self._stopped:
            return
        self._stopped = False
        self._schedule()

    def _schedule(self) -> None:
        delay = self._adjust(self._timeout_ms) / 1000.0
        self._task = asyncio.ensure_future(self._run(delay))

    async def _sleep(self, delay: float) -> None:
        """Sleep until ``delay`` elapses ON THE TIMER'S CLOCK."""
        clock = self._clock
        if clock is _clockmod.SYSTEM:
            await asyncio.sleep(delay)
            return
        deadline = clock.monotonic() + delay
        while True:
            rem = deadline - clock.monotonic()
            if rem <= 0:
                return
            # bounded slices: a frozen clock parks here (rem never
            # shrinks) without spinning, and a rate change lands within
            # one slice instead of after the stale full delay
            await asyncio.sleep(min(rem, self._SLICE_S))

    async def _run(self, delay: float) -> None:
        try:
            await self._sleep(delay)
            if self._stopped or self._destroyed:
                return
            await self._on_trigger()
        except asyncio.CancelledError:
            return
        except Exception:
            import logging

            logging.getLogger(__name__).exception("timer %s handler failed", self._name)
        # only the active generation reschedules: a restart() from inside
        # the handler already created a fresh task
        if (not self._stopped and not self._destroyed
                and self._task is asyncio.current_task()):
            self._schedule()

    def stop(self) -> None:
        self._stopped = True
        task, self._task = self._task, None
        # A handler may stop its own timer (e.g. _elect_self stopping the
        # election timer that fired it).  Cancelling the current task
        # would kill the handler at its next await — mark stopped instead;
        # _run won't reschedule.
        if task is not None and task is not asyncio.current_task():
            task.cancel()

    def restart(self) -> None:
        self.stop()
        self._stopped = False
        self._schedule()

    def reset_timeout(self, timeout_ms: int) -> None:
        self._timeout_ms = timeout_ms

    async def destroy(self) -> None:
        self._destroyed = True
        self.stop()

    @property
    def running(self) -> bool:
        return not self._stopped
