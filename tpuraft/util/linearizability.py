"""Linearizability checking for concurrent KV histories.

The reference validates distributed correctness with latch-style chaos
asserts (`test:core/NodeTest` kill/restart + convergence checks,
RheaKV chaos tests — SURVEY.md §5).  This module goes further: record
the real-time invoke/return windows of concurrent client operations and
*prove* the observed results admit a legal sequential order — the
linearizability promise raft-backed stores actually make (Herlihy &
Wing; checker in the style of Wing & Gong's DFS with Lowe's
state-memoization, as used by Knossos/porcupine).

Usage::

    h = History()
    tok = h.invoke(client_id, "w", (b"k", b"v1"))   # before the call
    h.complete(tok, True)                            # with the result
    ...
    report = check_history(h)        # partitions per key (linearizability
    assert report.ok                 # is compositional), checks each

Operations that never returned (client crashed / timed out / ambiguous
error) stay *pending*: the checker may linearize them at any point after
their invoke — or never (the op may not have taken effect).  This is
exactly the "info" semantics chaos histories need: a put whose ack was
lost to a leader kill is allowed, but not required, to be visible.

Scaling envelope: the search is worst-case exponential (linearizability
checking is NP-complete — Gibbons & Korach); memoization plus the
pending-op prunings below keep realistic histories tractable up to a
few thousand ops per key with up to a few hundred surviving pending
ops.  Pace recorders accordingly (a few ms between ops) — beyond that,
`max_states` raises instead of hanging.

Checked op kinds over a single key (a register):

==========  ======================  =======================================
kind        args                    result semantics
==========  ======================  =======================================
``w``       ``(key, value)``        write; result ignored (``True`` ack)
``r``       ``(key,)``              must return the current value (None if
                                    absent)
``cas``     ``(key, expect, upd)``  ``True`` iff state == expect (then
                                    state := upd)
``pia``     ``(key, value)``        put-if-absent: returns prior value;
                                    writes only if state is None
``del``     ``(key,)``              delete; result ignored
==========  ======================  =======================================
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Op:
    op_id: int
    client: int
    kind: str
    args: tuple
    invoke: float
    ret: Optional[float] = None      # None = pending (maybe applied)
    result: object = None

    @property
    def key(self) -> bytes:
        return self.args[0]

    def __str__(self) -> str:
        win = f"[{self.invoke:.6f}, " + (
            f"{self.ret:.6f}]" if self.ret is not None else "...)")
        return (f"op{self.op_id} c{self.client} {self.kind}"
                f"{self.args[1:] if len(self.args) > 1 else ''}"
                f" -> {self.result!r} {win}")


class History:
    """Thread-safe-enough recorder for one asyncio process: `invoke`
    before issuing the client call, `complete` with the observed result.
    An op never completed is pending — the checker treats it as
    maybe-applied."""

    def __init__(self) -> None:
        self._ops: list[Op] = []

    def invoke(self, client: int, kind: str, args: tuple,
               now: Optional[float] = None) -> int:
        op = Op(len(self._ops), client, kind, tuple(args),
                time.monotonic() if now is None else now)
        self._ops.append(op)
        return op.op_id

    def complete(self, op_id: int, result: object,
                 now: Optional[float] = None) -> None:
        op = self._ops[op_id]
        op.ret = time.monotonic() if now is None else now
        op.result = result

    def discard(self, op_id: int) -> None:
        """Forget an op known to have NOT executed (e.g. rejected
        client-side before any RPC left the process)."""
        self._ops[op_id].kind = "_discarded"

    def ops(self) -> list[Op]:
        return [o for o in self._ops if o.kind != "_discarded"]


# ---------------------------------------------------------------------------
# single-register model
# ---------------------------------------------------------------------------

def _apply(kind: str, args: tuple, result: object, completed: bool,
           state):
    """Try to linearize one op against register value ``state``.

    Returns the new state, or raises _Illegal if the op's *observed*
    result contradicts the model.  Pending ops (completed=False) have no
    observed result: any model outcome is acceptable."""
    if kind == "w":
        return args[1]
    if kind == "del":
        return None
    if kind == "r":
        if completed and state != result:
            raise _Illegal
        return state
    if kind == "cas":
        ok = state == args[1]
        if completed and bool(result) != ok:
            raise _Illegal
        return args[2] if ok else state
    if kind == "pia":
        if state is None:
            if completed and result is not None:
                raise _Illegal
            return args[1]
        if completed and result != state:
            raise _Illegal
        return state
    raise ValueError(f"unknown op kind {kind!r}")


class _Illegal(Exception):
    pass


def _prunable_pending(op: Op, key_ops: list[Op]) -> bool:
    """True if dropping this *pending* op cannot change the verdict.

    A pending read observes nothing and changes nothing: any witness
    containing it maps to one without it.  In a history whose ops are
    only writes/reads/deletes, a pending write of a value no completed
    read ever returned can likewise never be *required*: completed
    reads between it and the next state change would have had to return
    its value, so in every witness the interval it governs contains no
    completed observation — removing it leaves every completed op's
    legality unchanged.  (With CAS/put-if-absent in the history this
    does not hold — a failed CAS can observe "state != expect" — so no
    write pruning happens then.)  Pruning matters: chaos histories pile
    up maybe-applied ops, and each un-prunable pending op doubles the
    reachable linearization frontier.
    """
    if op.ret is not None:
        return False
    if op.kind == "r":
        return True
    if op.kind != "w":
        return False
    if any(o.kind not in ("w", "r", "del") for o in key_ops):
        return False
    v = op.args[1]
    return not any(o.ret is not None and o.kind == "r" and o.result == v
                   for o in key_ops)


# ---------------------------------------------------------------------------
# checker
# ---------------------------------------------------------------------------

@dataclass
class KeyReport:
    key: bytes
    ok: bool
    n_ops: int
    n_pending: int
    witness: list[int] = field(default_factory=list)   # op ids in order
    # on failure: the op set the search could never extend past
    stuck_ops: list[str] = field(default_factory=list)


@dataclass
class Report:
    ok: bool
    keys: dict[bytes, KeyReport]

    def __str__(self) -> str:
        bad = [k for k, r in self.keys.items() if not r.ok]
        if self.ok:
            total = sum(r.n_ops for r in self.keys.values())
            return (f"linearizable: {len(self.keys)} keys, {total} ops "
                    f"({sum(r.n_pending for r in self.keys.values())} pending)")
        lines = [f"NOT linearizable: keys {bad}"]
        for k in bad:
            r = self.keys[k]
            lines += [f"  key {k!r}:"] + [f"    {s}" for s in r.stuck_ops]
        return "\n".join(lines)


def check_register(ops: list[Op], initial=None,
                   max_states: int = 2_000_000) -> KeyReport:
    """Check one key's ops for linearizability against a register model.

    Iterative DFS over (linearized-set, register-state) with
    memoization.  All completed ops must be linearized; pending ops may
    be linearized (never before their invoke) or simply left unplaced —
    an op that never took effect.  Real-time order: op A must precede
    op B iff A.ret < B.invoke.  (Strict: exact timestamp ties are
    treated as concurrency.  Tie-as-precedence is NOT an order — two
    zero-duration ops at one instant would mutually precede each other
    and deadlock the search, failing valid histories; and monotonic-ns
    clocks make ties between genuinely ordered calls effectively
    impossible, so nothing real is lost.)
    """
    key = ops[0].key if ops else b""
    ops = [o for o in ops if not _prunable_pending(o, ops)]
    ops = sorted(ops, key=lambda o: o.invoke)
    n = len(ops)
    completed = [o.ret is not None for o in ops]
    completed_mask = sum(1 << i for i in range(n) if completed[i])
    n_pending = n - sum(completed)
    if n == 0:
        return KeyReport(key, True, 0, 0)
    rets = [o.ret if o.ret is not None else float("inf") for o in ops]

    def _candidates(done_mask: int):
        """Ops placeable next: not yet placed, and invoked no later than
        every unplaced completed op's return (an op whose return
        strictly precedes another's invoke must be linearized first).

        Ties (A.ret == B.invoke) are CONCURRENCY, not precedence: the
        strict `A.ret < B.invoke` order is what keeps precedence an
        interval order — treating ties as precedence makes two
        zero-duration ops at the same instant mutually precede each
        other (a cycle: neither is ever placeable) and falsely fails
        linearizable histories.  Hence `<=` below, matching the
        docstring's A.ret < B.invoke definition exactly."""
        min_ret = float("inf")
        for i in range(n):
            if not done_mask >> i & 1 and completed[i] and rets[i] < min_ret:
                min_ret = rets[i]
        out = []
        for i in range(n):
            if done_mask >> i & 1:
                continue
            if ops[i].invoke <= min_ret:
                out.append(i)
            else:
                break  # sorted by invoke; later ops can only be later
        return out

    seen: set = set()
    stack = [(0, initial)]                  # (done_mask, register value)
    parent: dict[tuple, tuple] = {}
    best_mask = 0

    while stack:
        node = stack.pop()
        if node in seen:
            continue
        seen.add(node)
        if len(seen) > max_states:
            raise RuntimeError(
                f"linearizability search exceeded {max_states} states "
                f"on key {key!r} ({n} ops) — shrink the history")
        done_mask, state = node
        if done_mask & completed_mask == completed_mask:
            witness = []
            cur = node
            while cur in parent:
                cur, op_i = parent[cur]
                witness.append(ops[op_i].op_id)
            witness.reverse()
            return KeyReport(key, True, n, n_pending, witness)
        if (done_mask & completed_mask).bit_count() > \
                (best_mask & completed_mask).bit_count():
            best_mask = done_mask
        for i in _candidates(done_mask):
            try:
                new_state = _apply(ops[i].kind, ops[i].args, ops[i].result,
                                   completed[i], state)
            except _Illegal:
                continue
            if not completed[i] and new_state == state:
                # a pending op linearized as a state no-op is
                # indistinguishable from dropping it — don't branch
                # (this is what keeps pending-heavy CAS histories from
                # exploding: a maybe-applied cas that would fail here
                # contributes nothing)
                continue
            nxt = (done_mask | 1 << i, new_state)
            if nxt not in seen:
                parent.setdefault(nxt, (node, i))
                stack.append(nxt)

    stuck = [str(ops[i]) for i in range(n)
             if completed[i] and not best_mask >> i & 1][:6]
    return KeyReport(key, False, n, n_pending, stuck_ops=stuck)


def check_history(history: History, initial=None) -> Report:
    """Partition a history by key (linearizability is compositional over
    independent objects) and check each key's register history."""
    by_key: dict[bytes, list[Op]] = {}
    for op in history.ops():
        by_key.setdefault(op.key, []).append(op)
    keys = {k: check_register(v, initial=initial)
            for k, v in sorted(by_key.items())}
    return Report(all(r.ok for r in keys.values()), keys)


# ---------------------------------------------------------------------------
# targeted staleness assertion (read-mix soaks)
# ---------------------------------------------------------------------------

def check_stale_reads(ops: list[Op], seq_of) -> list[str]:
    """Fast, targeted no-stale-read assertion for monotone single-writer
    histories: a completed read must observe every write acked before
    the read was ISSUED.

    Requires the workload to write per-key monotonically increasing
    sequence values with at most ONE writer per key issuing writes in
    order (the read-mix soak's shape); ``seq_of(value) -> int`` extracts
    the sequence (return -1 for None/garbage).  A read is stale iff its
    observed sequence is below the highest sequence acked before its
    invoke AND is not explained by a maybe-applied (pending) write that
    could legally linearize later — a timed-out lower-seq write landing
    in the log after its successor is linearizable, not stale.

    Complements (does not replace) ``check_history``: the full checker
    proves the whole history, this one gives an O(n log n) verdict with
    a per-read violation message naming exactly which acked write the
    read missed — and stays tractable at read volumes that would swamp
    the exponential search.
    """
    import bisect

    violations: list[str] = []
    by_key: dict[bytes, list[Op]] = {}
    for op in ops:
        by_key.setdefault(op.key, []).append(op)
    for key, key_ops in sorted(by_key.items()):
        writes = [o for o in key_ops if o.kind == "w"]
        acked = sorted((o for o in writes if o.ret is not None),
                       key=lambda o: o.ret)
        # prefix-max of (seq, op) over the ack-ordered writes: the floor
        # for a read is one bisect on its invoke time, not a rescan of
        # every acked write (keeps the checker O(n log n) on the
        # read-heavy histories it exists for)
        ack_rets: list[float] = []
        prefix: list[tuple[int, Op]] = []
        best_seq, best_op = -1, None
        for w in acked:
            s = seq_of(w.args[1])
            if s > best_seq:
                best_seq, best_op = s, w
            ack_rets.append(w.ret)
            prefix.append((best_seq, best_op))
        # maybe-applied writes: seq -> earliest invoke (a pending write
        # may legally linearize any time after its invoke)
        pending_invoke: dict[int, float] = {}
        for w in writes:
            if w.ret is None:
                s = seq_of(w.args[1])
                if s not in pending_invoke or w.invoke < pending_invoke[s]:
                    pending_invoke[s] = w.invoke
        for read in key_ops:
            if read.kind != "r" or read.ret is None:
                continue
            # highest sequence fully acked before this read was issued
            i = bisect.bisect_right(ack_rets, read.invoke)
            if i == 0:
                continue
            floor_seq, floor_op = prefix[i - 1]
            got = seq_of(read.result)
            if got >= floor_seq:
                continue
            # a maybe-applied write invoked before the read returned may
            # legally linearize between the floor write and the read
            if pending_invoke.get(got, float("inf")) <= read.ret:
                continue
            violations.append(
                f"stale read on {key!r}: {read} observed seq {got} but "
                f"{floor_op} (seq {floor_seq}) was acked "
                f"{(read.invoke - floor_op.ret) * 1e3:.1f}ms before the "
                f"read was issued")
    return violations
