"""Shared build-or-dlopen logic for the native C++ engines.

Used by multilog / logstore / transport / kvstore loaders.  Three
deployment shapes must all work:

  1. dev checkout (toolchain + writable dir): rebuild when sources are
     newer than the .so, under a cross-process flock so concurrently
     spawned stores never dlopen a half-written file;
  2. read-only install (no writable dir — the flock file itself cannot
     be created): nobody can be mid-build either, so dlopen the
     existing .so directly;
  3. toolchain-free host (make missing/failing): fall back to an
     existing .so with a warning instead of refusing to open storage.
"""

from __future__ import annotations

import glob
import logging
import os
import subprocess

LOG = logging.getLogger("tpuraft.native_build")


def _sources_mtime(native_dir: str) -> float:
    newest = 0.0
    for pat in ("*.cc", "*.h", "Makefile"):
        for p in glob.glob(os.path.join(native_dir, pat)):
            try:
                newest = max(newest, os.path.getmtime(p))
            except OSError:
                pass
    return newest


def _so_current(native_dir: str, path: str) -> bool:
    try:
        return os.path.getmtime(path) >= _sources_mtime(native_dir)
    except OSError:
        return False  # .so missing


def ensure_built(native_dir: str, lib_path: str, target: str | None = None,
                 timeout: float = 120.0) -> str:
    """Return the path of an up-to-date ``lib_path``, rebuilding via
    ``make -C native_dir`` only when sources are newer than the .so.

    A ``lib_path`` outside ``native_dir`` is a prebuilt override (the
    TPURAFT_NATIVE_*_LIB env vars): returned as-is, never rebuilt."""
    native_dir = os.path.normpath(native_dir)
    path = lib_path
    if os.path.dirname(os.path.normpath(path)) != native_dir:
        return path
    lock_path = os.path.join(native_dir, ".build.lock")
    try:
        lock = open(lock_path, "w")
    except OSError:
        # unwritable package dir (read-only install): no process can be
        # mid-build here, so the existing .so cannot be half-written
        if os.path.exists(path):
            if not _so_current(native_dir, path):
                LOG.warning("%s: package dir read-only and %s is older "
                            "than sources; dlopening it anyway", native_dir,
                            os.path.basename(path))
            return path
        raise
    import fcntl

    with lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        # re-check under the lock: a concurrent spawner may have just
        # finished the build while we waited
        if _so_current(native_dir, path):
            return path
        cmd = ["make", "-C", native_dir] + ([target] if target else [])
        try:
            subprocess.run(cmd, check=True, timeout=timeout,
                           capture_output=True)
        except (OSError, subprocess.SubprocessError) as exc:
            if os.path.exists(path):
                LOG.warning("native build failed (%s); falling back to "
                            "existing %s", exc, path)
                return path
            raise
    return path
