"""Per-region heat telemetry: decayed-window EWMA access rates.

*Nezha* (PAPERS.md) keys its raft-friendly KV layout decisions off
per-region access telemetry; ROADMAP item 2's heat-driven split/merge/
move needs the same signal.  This module is the intake side: a
:class:`RegionHeatTracker` lives on each store, is fed O(1) from the
serving hot paths (kv_service write admission / read serve) and the FSM
apply loop, and folds the accumulated counts into per-region EWMAs of
writes/s, reads/s and bytes in/out at the PD-heartbeat cadence.

Design choices (docs/architecture.md "Heat is EWMA-decayed server-side"):

- **Accumulate-then-fold**, not per-op EWMA math: the hot path does one
  dict lookup and a few float adds per op (``note_write``/``note_read``
  are on the kv_command_batch item loop); all rate math runs once per
  fold (heartbeat interval), so heat accounting stays inside the
  bench-gate's 3% overhead budget at any op rate.
- **Decay on the server, raw counts never cross the wire**: each fold
  applies ``alpha = 1 - 0.5^(dt / half_life)`` so a silent region's
  rates glide to zero without the PD having to remember per-region
  timestamps for thousands of regions x stores, and a PD failover
  starts from the stores' standing EWMAs (one full heartbeat resync)
  instead of replaying history.
- **Noise-gated reporting**: :func:`heat_changed` mirrors the PR 3
  delta plane's keys gate (~12.5% relative move) so steady heat does
  not defeat delta-batched heartbeats.

Seeded-deterministic: the clock is injectable, fold math is pure, and a
test driving ``note_* + fold`` by hand gets byte-identical rates.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass

# one wire row: region_id + 4 float32 rates (writes/s, reads/s,
# bytes_in/s, bytes_out/s) — 24 bytes; rides the delta-batched PD
# heartbeat as a trailing bytes field (pd_messages.encode_heat_rows)
_HEAT_ROW = struct.Struct("<qffff")

# rates below this (ops/s or bytes/s scaled) are "cold enough to forget"
_EPS = 1e-3


# graftcheck: loop-confined — rows live inside RegionHeatTracker's
# rates dict and are folded/served only on the owning store's loop;
# the exposition thread reads plain floats (best-effort, like counters)
@dataclass
class RegionHeat:
    """One region's decayed access rates (all per second)."""

    writes_s: float = 0.0
    reads_s: float = 0.0
    bytes_in_s: float = 0.0
    bytes_out_s: float = 0.0
    # replication-side apply rate (ops applied by the local FSM) —
    # follower load visibility; NOT folded into the serving score and
    # not reported to the PD (leaders' serving rates already cover it)
    applied_s: float = 0.0

    @property
    def score(self) -> float:
        return heat_score(self.writes_s, self.reads_s,
                          self.bytes_in_s, self.bytes_out_s)


def heat_score(writes_s: float, reads_s: float,
               bytes_in_s: float, bytes_out_s: float) -> float:
    """Scalar hot/cold ranking: ops dominate, payload bytes weigh in at
    one op per 4KiB so a few huge-value streams still register.  ONE
    definition shared by the store tracker and the PD's ClusterView /
    hot-region detection — two scores would rank differently."""
    return writes_s + reads_s + (bytes_in_s + bytes_out_s) / 4096.0


def heat_changed(new_score: float, last_score: float,
                 min_abs: float = 0.5) -> bool:
    """Report-worthiness gate (mirrors the delta plane's keys gate): a
    score move under ~12.5% relative AND under ``min_abs`` ops/s is
    noise — steady heat must not re-dirty the heartbeat every round."""
    delta = abs(new_score - last_score)
    if delta < min_abs:
        return False
    return delta * 8.0 >= max(last_score, min_abs)


# graftcheck: loop-confined — note_*/fold/snapshot run on the owning
# store's event loop (kv handlers, FSM caller, the PD heartbeat loop);
# the metrics exposition thread only reads plain floats out of the
# rates dict (best-effort consistency, like every other counter there)
class RegionHeatTracker:
    """Per-store, per-region decayed-window access telemetry.

    Hot path: :meth:`note_write` / :meth:`note_read` /
    :meth:`note_applied` accumulate raw counts O(1).  Cadence path:
    :meth:`fold` (PD heartbeat loop) turns the window's counts into
    rates and decays idle regions; :meth:`heat` / :meth:`top` /
    :meth:`coldest` serve the standing EWMAs.
    """

    def __init__(self, half_life_s: float = 10.0, clock=time.monotonic):
        self.half_life_s = max(half_life_s, 1e-3)
        self._clock = clock
        # region -> [writes, reads, bytes_in, bytes_out, applied] since
        # the last fold (raw counts, not rates)
        self._acc: dict[int, list] = {}
        self._rates: dict[int, RegionHeat] = {}
        self._last_fold = clock()
        # monotonic counters (exposition)
        self.writes_noted = 0
        self.reads_noted = 0
        self.applied_noted = 0
        self.folds = 0

    # -- hot-path intake -----------------------------------------------------

    def _bucket(self, region_id: int) -> list:
        b = self._acc.get(region_id)
        if b is None:
            b = self._acc[region_id] = [0.0, 0.0, 0.0, 0.0, 0.0]
        return b

    def note_write(self, region_id: int, ops: int = 1,
                   bytes_in: int = 0) -> None:
        b = self._bucket(region_id)
        b[0] += ops
        b[2] += bytes_in
        self.writes_noted += ops

    def note_read(self, region_id: int, ops: int = 1,
                  bytes_out: int = 0) -> None:
        b = self._bucket(region_id)
        b[1] += ops
        b[3] += bytes_out
        self.reads_noted += ops

    def note_applied(self, region_id: int, ops: int = 1) -> None:
        b = self._bucket(region_id)
        b[4] += ops
        self.applied_noted += ops

    # -- cadence path --------------------------------------------------------

    def fold(self, now: float | None = None) -> float:
        """Fold the accumulated window into the EWMAs; returns the
        window length in seconds (0.0 = clock didn't advance, nothing
        folded).  Regions whose every rate decayed below noise AND saw
        no traffic this window are forgotten — the maps stay bounded by
        the live working set, not by region-id history."""
        if now is None:
            now = self._clock()
        dt = now - self._last_fold
        if dt <= 0.0:
            return 0.0
        self._last_fold = now
        alpha = 1.0 - 0.5 ** (dt / self.half_life_s)
        acc, self._acc = self._acc, {}
        dead: list[int] = []
        for rid in self._rates.keys() | acc.keys():
            b = acc.get(rid)
            h = self._rates.get(rid)
            if h is None:
                h = self._rates[rid] = RegionHeat()
            w, r, bi, bo, ap = (x / dt for x in b) if b else (0.0,) * 5
            h.writes_s += alpha * (w - h.writes_s)
            h.reads_s += alpha * (r - h.reads_s)
            h.bytes_in_s += alpha * (bi - h.bytes_in_s)
            h.bytes_out_s += alpha * (bo - h.bytes_out_s)
            h.applied_s += alpha * (ap - h.applied_s)
            if b is None and h.score < _EPS and h.applied_s < _EPS:
                dead.append(rid)
        for rid in dead:
            del self._rates[rid]
        self.folds += 1
        return dt

    # -- reads ---------------------------------------------------------------

    def heat(self, region_id: int) -> RegionHeat:
        return self._rates.get(region_id) or RegionHeat()

    def snapshot(self) -> dict[int, RegionHeat]:
        return dict(self._rates)

    def top(self, k: int) -> list[tuple[int, RegionHeat]]:
        """Hottest k tracked regions, descending score."""
        return sorted(self._rates.items(),
                      key=lambda kv: -kv[1].score)[:max(0, k)]

    def coldest(self, k: int) -> list[tuple[int, RegionHeat]]:
        """Coldest k tracked regions, ascending score (only regions the
        tracker still remembers — fully-forgotten regions are colder
        still, but carry no information)."""
        return sorted(self._rates.items(),
                      key=lambda kv: kv[1].score)[:max(0, k)]

    def drop(self, region_id: int) -> None:
        """This region's standing rates no longer describe its keyspace
        — a split just moved half of it (StoreEngine.do_split), or the
        region left the store (merge/move, when that lands): forget
        them and re-accumulate from live traffic."""
        self._acc.pop(region_id, None)
        self._rates.pop(region_id, None)

    # -- observability -------------------------------------------------------

    def counters(self) -> dict:
        return {
            "heat_writes_noted": self.writes_noted,
            "heat_reads_noted": self.reads_noted,
            "heat_applied_noted": self.applied_noted,
            "heat_folds": self.folds,
        }

    def gauges(self) -> dict:
        top = self.top(1)
        return {
            "heat_regions_tracked": len(self._rates),
            "heat_top_score": round(top[0][1].score, 3) if top else 0.0,
        }

    def describe(self) -> str:
        rows = ", ".join(
            f"r{rid}={h.score:.1f}(w{h.writes_s:.1f}/r{h.reads_s:.1f})"
            for rid, h in self.top(4)) or "-"
        return (f"RegionHeatTracker<regions={len(self._rates)} "
                f"writes={self.writes_noted} reads={self.reads_noted} "
                f"applied={self.applied_noted} folds={self.folds} "
                f"top=[{rows}]>")


# -- wire codec (PD heartbeat trailing field) --------------------------------


def encode_heat_rows(rows: list[tuple[int, float, float, float, float]]
                     ) -> bytes:
    """Pack (region_id, writes_s, reads_s, bytes_in_s, bytes_out_s)
    rows for the StoreHeartbeatBatchRequest trailing ``heat`` field;
    an empty list packs to b"" (zero wire cost when nothing moved)."""
    if not rows:
        return b""
    return b"".join(_HEAT_ROW.pack(rid, w, r, bi, bo)
                    for rid, w, r, bi, bo in rows)


def decode_heat_rows(blob: bytes
                     ) -> list[tuple[int, float, float, float, float]]:
    """Tolerant decode: a short/absent blob (old sender) yields no
    rows; a trailing partial row is ignored rather than raising."""
    if not blob:
        return []
    n = len(blob) // _HEAT_ROW.size
    return [_HEAT_ROW.unpack_from(blob, i * _HEAT_ROW.size)
            for i in range(n)]
