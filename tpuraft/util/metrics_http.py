"""Shared stdlib /metrics HTTP listener.

One implementation serving Prometheus text on a daemon thread, used by
both the store engine (``StoreEngineOptions.metrics_port``) and the
placement driver (``PlacementDriverOptions.metrics_port``) — the
listener only calls the ``render`` callable per GET and never mutates
component state (best-effort consistency by design; renders that only
read counters are safe from this thread)."""

from __future__ import annotations

import http.server
import logging
import threading
from typing import Callable

LOG = logging.getLogger(__name__)


# concurrency contract (graftcheck-reviewed, deliberately NOT
# loop-confined): the handler runs on ThreadingHTTPServer daemon
# threads.  Every attribute below is published BEFORE the serving
# thread starts and never rebound afterwards (immutable-after-publish);
# the render callable itself must only read counters or snapshot
# copies — the contract each metrics_text() implementation documents
class MetricsHttpServer:
    """GET /metrics (or /) -> ``render()`` as Prometheus text.

    ``port=0`` binds ephemerally; the bound port is in :attr:`port`.
    """

    def __init__(self, host: str, port: int, render: Callable[[], str],
                 name: str = "metrics-http"):
        srv = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler contract
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = srv._render().encode()
                except Exception as e:  # noqa: BLE001 — racing a split
                    self.send_error(500, str(e)[:100])
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes aren't news
                pass

        self._render = render
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.port: int = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=name, daemon=True)
        self._thread.start()
        LOG.info("%s serving /metrics on %s:%d", name, host, self.port)

    def shutdown_blocking(self) -> None:
        """Stop serving; blocks up to the poll interval — call it off
        the event loop (run_in_executor)."""
        self._httpd.shutdown()
        self._httpd.server_close()
