"""Store health scoring: gray-failure detection from hot-path signals.

Fail-slow is the production failure mode the chaos harness never
modeled: a store with a stalling disk or a saturated CPU stays "alive"
to every existing check (it acks heartbeats, eventually) while every
group it leads limps at 100x latency.  *CD-Raft* (PAPERS.md) treats
degraded links as the normal case and routes around them;
*Compartmentalization* isolates stages so one slow component cannot
stall the rest — this module gives stores the same posture: score each
store's health from signals the hot path ALREADY produces, and let the
mitigation layers (leadership evacuation, read re-routing, serving-
plane shedding — tpuraft/rheakv/store_engine.py, kv_service.py,
pd_server.py) act on the score.

Signals (no new RPCs, no polling probes):
  - **disk**: append+fsync latency of every log flush round
    (``LogManager._flush_loop`` times the storage call; the multilog
    group-commit feeds its in-thread fsync duration) plus the AGE of a
    still-in-flight flush — a fully hung fsync produces no completed
    sample, so the EMA alone would never notice it;
  - **peer RTT**: ack round-trip of every beat-plane RPC the
    HeartbeatHub / ReadConfirmBatcher / classic heartbeat path already
    sends, per destination endpoint;
  - **apply backlog**: committed-minus-applied depth the FSMCaller
    already tracks.

Scoring is DETERMINISTIC given the same inputs: ``evaluate()`` folds
the EMAs through fixed thresholds into {HEALTHY, DEGRADED, SICK} with
evaluation-count hysteresis (a score only worsens after
``worsen_after`` consecutive bad evaluations and only improves after
``recover_after`` consecutive good ones), so one writeback spike never
flaps leadership and a recovering store must PROVE health before the
evacuation brake releases.  No wall-clock policy: hysteresis counts
evaluation rounds, not seconds — a seeded test drives evaluate() by
hand and gets byte-identical transitions.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

HEALTHY = "healthy"
DEGRADED = "degraded"
SICK = "sick"

_LEVELS = {HEALTHY: 0, DEGRADED: 1, SICK: 2}

# disk-pressure states (DiskBudget) — same hysteresis machinery, its
# own axis: pressure feeds health as an external FLOOR (NEAR_FULL =>
# DEGRADED, FULL => SICK) rather than mixing into the latency signals
PRESSURE_OK = "ok"
PRESSURE_NEAR_FULL = "near_full"
PRESSURE_FULL = "full"

_PRESSURE_LEVELS = {PRESSURE_OK: 0, PRESSURE_NEAR_FULL: 1,
                    PRESSURE_FULL: 2}


@dataclass
class HealthOptions:
    """Thresholds + hysteresis for one store's tracker.

    Defaults target the same-host chaos/soak envelope (sub-ms healthy
    fsyncs); production disks tune disk_* up.  See docs/operations.md
    "Gray-failure runbook"."""

    # disk: flush-round latency EMA (ms) — append + fsync, as observed
    # by the LogManager flush loop / multilog group commit
    disk_degraded_ms: float = 25.0
    disk_sick_ms: float = 120.0
    # a flush IN FLIGHT longer than this is a stall even with a clean
    # EMA (a hung fsync completes no sample); scored SICK directly
    disk_stall_ms: float = 500.0
    # peer ack RTT EMA (ms): scores the PEER endpoint, not this store
    peer_degraded_ms: float = 50.0
    peer_sick_ms: float = 250.0
    # apply backlog: committed-minus-applied entries (EMA) across groups
    apply_degraded: float = 256.0
    apply_sick: float = 2048.0
    # event-loop scheduling lag EMA (ms): delay between when a timer
    # callback was DUE and when the loop actually ran it — the direct
    # signal of a saturated loop (the single-process store fabric's
    # ceiling; see docs/operations.md "Process topology runbook").
    # Thresholds are deliberately loose: test topologies multiplex many
    # stores on one loop and boot storms spike lag transiently — the
    # hysteresis plus these bounds keep that from flapping leadership.
    loop_degraded_ms: float = 250.0
    loop_sick_ms: float = 2000.0
    # probe cadence (4 extra callbacks/s at the default)
    loop_probe_interval_ms: float = 250.0
    # hysteresis (evaluation rounds, not seconds): worsen fast, recover
    # slowly — a DEGRADED-but-recovering store keeps its leaders
    worsen_after: int = 2
    recover_after: int = 5
    # EMA smoothing factor for new samples
    alpha: float = 0.25


# Fed from EXECUTOR threads (FileLogStorage appends run off-loop; the
# multilog group commit times its fsync in the executor) as well as the
# event loop — the one piece of tracker state that genuinely crosses
# threads, so it carries its own lock while the tracker stays
# loop-confined.
class DiskLatencyProbe:
    """Append/fsync latency EMA + in-flight stall age for one store."""

    def __init__(self, alpha: float = 0.25, clock=time.monotonic):
        self._alpha = alpha
        self._clock = clock
        self._lock = threading.Lock()
        self._ema_ms = 0.0            # guarded-by: _lock
        self._samples = 0             # guarded-by: _lock
        # flush begin timestamps keyed by token (in-flight rounds);
        # a hung fsync never ends its token, and its AGE is the signal
        self._inflight: dict[int, float] = {}   # guarded-by: _lock
        self._next_token = 0          # guarded-by: _lock

    def begin(self) -> int:
        """A flush round started; returns the token for :meth:`end`.
        begin/end feed ONLY the in-flight stall age — a hung fsync
        completes no sample, and its growing age is the signal."""
        with self._lock:
            self._next_token += 1
            tok = self._next_token
            self._inflight[tok] = self._clock()
            return tok

    def end(self, token: int) -> None:
        """The round completed (clears its stall-age token).  The EMA
        is deliberately NOT fed here: end-to-end round time includes
        executor-queue and event-loop wait, and in a co-hosted process
        one store's genuinely slow disk saturating the shared executor
        would score every OTHER store's disk sick too (observed as a
        mutual-evacuation leadership storm in the gray A/B bench).
        Feed the EMA with :meth:`note` from IN-THREAD measurements."""
        with self._lock:
            self._inflight.pop(token, None)

    def note(self, dur_s: float) -> None:
        """One completed disk op, measured IN the thread that did the
        I/O (LogManager's executor wrapper, the multilog group-commit's
        fsync timer) — the uncontaminated latency of THIS store's
        disk."""
        with self._lock:
            self._note_locked(dur_s * 1000.0)

    def _note_locked(self, ms: float) -> None:
        if self._samples == 0:
            self._ema_ms = ms
        else:
            self._ema_ms += self._alpha * (ms - self._ema_ms)
        self._samples += 1

    def snapshot(self) -> tuple[float, float, int]:
        """(ema_ms, oldest_inflight_age_ms, samples) — one locked read."""
        with self._lock:
            age = 0.0
            if self._inflight:
                now = self._clock()
                age = (now - min(self._inflight.values())) * 1000.0
            return self._ema_ms, age, self._samples


# graftcheck: loop-confined — armed, ticked and sampled on the owning
# store's event loop (call_later chain); stop() flips a flag the next
# tick observes
class LoopLagProbe:
    """Event-loop scheduling delay EMA: a ``call_later`` chain measures
    (actual - expected) run time of each tick.  A loop saturated by
    callback herds runs timers LATE — that lateness is exactly the
    latency every other callback on the loop is paying, so it scores
    the store's serving plane the way the disk probe scores its log
    plane.  Samples feed an EMA (+ a peak-hold max for triage);
    ``snapshot()`` is the tracker's read."""

    def __init__(self, alpha: float = 0.25, interval_s: float = 0.25,
                 clock=time.monotonic):
        self._alpha = alpha
        self._interval = interval_s
        self._clock = clock
        self._ema_ms = 0.0
        self._max_ms = 0.0
        self._samples = 0
        self._expected = 0.0
        self._handle = None
        self._running = False

    def start(self) -> None:
        """Arm the chain on the CURRENT running loop (idempotent)."""
        if self._running:
            return
        import asyncio

        self._running = True
        self._arm(asyncio.get_running_loop())

    def stop(self) -> None:
        self._running = False
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def _arm(self, loop) -> None:
        self._expected = self._clock() + self._interval
        self._handle = loop.call_later(self._interval, self._tick, loop)

    def _tick(self, loop) -> None:
        if not self._running:
            return
        lag = (self._clock() - self._expected) * 1000.0
        if lag < 0.0:
            lag = 0.0
        if self._samples == 0:
            self._ema_ms = lag
        else:
            self._ema_ms += self._alpha * (lag - self._ema_ms)
        if lag > self._max_ms:
            self._max_ms = lag
        self._samples += 1
        self._arm(loop)

    def snapshot(self) -> tuple[float, float, int]:
        """(ema_ms, max_ms, samples)."""
        return self._ema_ms, self._max_ms, self._samples


# graftcheck: loop-confined — owned by HealthTracker (self + per-peer
# rows), folded only on the store's event loop; the cross-thread disk
# signal stays inside the LOCKED DiskLatencyProbe above
class _Hysteresis:
    """Evaluation-count hysteresis around a raw level stream.

    ``levels`` maps level name -> rank (worse = higher); defaults to the
    health axis, and DiskBudget reuses the machinery with the pressure
    axis (OK/NEAR_FULL/FULL)."""

    __slots__ = ("level", "_pending", "_streak", "_up", "_down", "_levels")

    def __init__(self, worsen_after: int, recover_after: int,
                 levels: dict | None = None, initial: str = HEALTHY):
        self._levels = levels if levels is not None else _LEVELS
        self.level = initial
        self._pending = initial
        self._streak = 0
        self._up = max(1, worsen_after)
        self._down = max(1, recover_after)

    def fold(self, raw: str) -> str:
        if raw == self.level:
            self._pending, self._streak = raw, 0
            return self.level
        if raw != self._pending:
            self._pending, self._streak = raw, 0
        self._streak += 1
        need = self._up if self._levels[raw] > self._levels[self.level] \
            else self._down
        if self._streak >= need:
            self.level = raw
            self._streak = 0
        return self.level


# graftcheck: loop-confined — note_peer_rtt/note_apply_depth/evaluate
# run on the owning store's event loop (hub acks, FSM caller, the
# store's health task); only the disk probe above crosses threads
class HealthTracker:
    """One store's {HEALTHY, DEGRADED, SICK} score + per-peer scores."""

    def __init__(self, opts: HealthOptions | None = None,
                 clock=time.monotonic, label: str = ""):
        self.opts = opts or HealthOptions()
        # flight-recorder identity (the owning store's endpoint)
        self.label = label
        self.disk = DiskLatencyProbe(self.opts.alpha, clock=clock)
        # event-loop lag probe: started by the owning store's engine
        # (StoreEngine.start) — it needs a running loop to arm
        self.loop_lag = LoopLagProbe(
            self.opts.alpha,
            interval_s=self.opts.loop_probe_interval_ms / 1000.0,
            clock=clock)
        self._self_hyst = _Hysteresis(self.opts.worsen_after,
                                      self.opts.recover_after)
        # peer endpoint -> (rtt ema ms, samples, hysteresis)
        self._peers: dict[str, list] = {}
        self._apply_ema = 0.0
        self._apply_samples = 0
        self.evaluations = 0
        # observability: evaluations that saw each level, raw cause of
        # the current level ("disk" / "stall" / "apply" / "")
        self.level_counts = {HEALTHY: 0, DEGRADED: 0, SICK: 0}
        self.cause = ""
        # external raw floor (disk pressure): the DiskBudget ladder
        # pins the raw level at least this bad each round, so NEAR_FULL
        # rides the existing health heartbeat wire to the PD (stops new
        # leader placement) and FULL engages the SICK machinery
        # (evacuation + shed) without a second reporting channel
        self._floor = HEALTHY
        self._floor_cause = ""

    # -- signal intake -------------------------------------------------------

    def set_floor(self, level: str, cause: str = "") -> None:
        """Pin the RAW level at least this bad (hysteresis still
        applies).  HEALTHY clears the floor."""
        self._floor = level
        self._floor_cause = cause if level != HEALTHY else ""

    def note_peer_rtt(self, endpoint: str, rtt_s: float) -> None:
        ent = self._peers.get(endpoint)
        ms = rtt_s * 1000.0
        if ent is None:
            self._peers[endpoint] = [ms, 1, _Hysteresis(
                self.opts.worsen_after, self.opts.recover_after)]
            return
        ent[0] += self.opts.alpha * (ms - ent[0])
        ent[1] += 1

    def note_apply_depth(self, depth: int) -> None:
        self._apply_ema += self.opts.alpha * (depth - self._apply_ema)
        self._apply_samples += 1

    # -- scoring -------------------------------------------------------------

    def _raw_self(self) -> tuple[str, str]:
        o = self.opts
        ema, stall_age, samples = self.disk.snapshot()
        if stall_age >= o.disk_stall_ms:
            return SICK, "stall"
        level, cause = HEALTHY, ""
        if samples:
            if ema >= o.disk_sick_ms:
                level, cause = SICK, "disk"
            elif ema >= o.disk_degraded_ms:
                level, cause = DEGRADED, "disk"
        if self._apply_samples and _LEVELS[level] < _LEVELS[SICK]:
            if self._apply_ema >= o.apply_sick:
                level, cause = SICK, "apply"
            elif self._apply_ema >= o.apply_degraded \
                    and _LEVELS[level] < _LEVELS[DEGRADED]:
                level, cause = DEGRADED, "apply"
        lag_ema, _lag_max, lag_samples = self.loop_lag.snapshot()
        if lag_samples and _LEVELS[level] < _LEVELS[SICK]:
            if lag_ema >= o.loop_sick_ms:
                level, cause = SICK, "loop"
            elif lag_ema >= o.loop_degraded_ms \
                    and _LEVELS[level] < _LEVELS[DEGRADED]:
                level, cause = DEGRADED, "loop"
        if _LEVELS[self._floor] > _LEVELS[level]:
            level, cause = self._floor, self._floor_cause
        return level, cause

    def evaluate(self) -> str:
        """One scoring round: fold the current EMAs through the
        thresholds and the hysteresis; returns the (hysteretic) level.
        Call at a steady cadence (the store's health task) — hysteresis
        counts these calls, so cadence x worsen_after bounds detection
        latency."""
        from tpuraft.util.trace import RECORDER

        self.evaluations += 1
        prev = self._self_hyst.level
        raw, cause = self._raw_self()
        level = self._self_hyst.fold(raw)
        if level == raw:
            self.cause = cause
        if level != prev:
            # flight recorder: health transitions are incident markers,
            # and a SICK transition snapshots the ring — the lead-up
            # (elections, shed bounces, fence failures) must survive
            # ring churn for post-hoc triage
            RECORDER.record("health", self.label,
                            level=level, was=prev, cause=self.cause)
            if level == SICK:
                RECORDER.note_anomaly(
                    "sick_transition",
                    f"{self.label or 'store'}: {prev} -> {level} "
                    f"(cause={self.cause or '?'})")
        self.level_counts[level] += 1
        for ent in self._peers.values():
            o = self.opts
            if ent[0] >= o.peer_sick_ms:
                praw = SICK
            elif ent[0] >= o.peer_degraded_ms:
                praw = DEGRADED
            else:
                praw = HEALTHY
            ent[2].fold(praw)
        return level

    def score(self) -> str:
        """Current hysteretic level (no new evaluation round)."""
        return self._self_hyst.level

    def peer_score(self, endpoint: str) -> str:
        ent = self._peers.get(endpoint)
        return ent[2].level if ent is not None else HEALTHY

    def slow_peers(self) -> list[str]:
        """Endpoints currently scored worse than HEALTHY."""
        return sorted(ep for ep, ent in self._peers.items()
                      if ent[2].level != HEALTHY)

    # -- observability -------------------------------------------------------

    def counters(self) -> dict:
        ema, stall_age, samples = self.disk.snapshot()
        lag_ema, lag_max, lag_samples = self.loop_lag.snapshot()
        return {
            "health_level": _LEVELS[self.score()],
            "health_evaluations": self.evaluations,
            "health_disk_ema_ms": round(ema, 3),
            "health_disk_inflight_ms": round(stall_age, 1),
            "health_disk_samples": samples,
            "health_apply_ema": round(self._apply_ema, 1),
            "health_loop_lag_ms": round(lag_ema, 3),
            "health_loop_lag_max_ms": round(lag_max, 1),
            "health_loop_samples": lag_samples,
            "health_slow_peers": len(self.slow_peers()),
        }

    def register_gauges(self, metrics) -> None:
        metrics.gauge("health.level", lambda: _LEVELS[self.score()])
        metrics.gauge("health.disk_ema_ms",
                      lambda: self.disk.snapshot()[0])
        metrics.gauge("health.disk_inflight_ms",
                      lambda: self.disk.snapshot()[1])
        metrics.gauge("health.apply_ema", lambda: self._apply_ema)
        metrics.gauge("health.loop_lag_ms",
                      lambda: self.loop_lag.snapshot()[0])
        metrics.gauge("health.loop_lag_max_ms",
                      lambda: self.loop_lag.snapshot()[1])
        metrics.gauge("health.slow_peers",
                      lambda: float(len(self.slow_peers())))

    def describe(self) -> str:
        ema, stall_age, samples = self.disk.snapshot()
        lag_ema, lag_max, _n = self.loop_lag.snapshot()
        peers = ", ".join(
            f"{ep}={ent[2].level}:{ent[0]:.1f}ms"
            for ep, ent in sorted(self._peers.items())) or "-"
        return (f"HealthTracker<{self.score()} cause={self.cause or '-'} "
                f"disk_ema={ema:.2f}ms inflight={stall_age:.0f}ms "
                f"samples={samples} apply_ema={self._apply_ema:.1f} "
                f"loop_lag={lag_ema:.1f}ms max={lag_max:.0f}ms "
                f"evals={self.evaluations} peers=[{peers}]>")


# ---------------------------------------------------------------------------
# disk-pressure accounting (capacity, not latency)
# ---------------------------------------------------------------------------


@dataclass
class DiskBudgetOptions:
    """Thresholds + hysteresis for one store's capacity tracker.

    See docs/operations.md "Disk-pressure runbook"."""

    # byte budget for the store's data directory.  0 = derive capacity
    # from statvfs at reconcile time (whole-filesystem accounting)
    budget_bytes: int = 0
    # pressure thresholds as fractions of the budget.  full_frac < 1.0
    # is the RESERVED HEADROOM: admission stops at full_frac so that
    # reclaim's own writes (snapshot temp dirs, journal-compaction tmp
    # files) still fit under the hard budget — otherwise a full store
    # could never compact its way back out (the classic deadlock)
    near_full_frac: float = 0.80
    full_frac: float = 0.92
    # hysteresis (evaluation rounds): worsen fast — usage is monotonic
    # between reclaims, not noisy — recover only once reclaim has
    # PROVEN space back
    worsen_after: int = 1
    recover_after: int = 2
    # rounds the raw level is pinned FULL after an observed ENOSPC,
    # regardless of the usage estimate: the disk itself voted
    enospc_latch_rounds: int = 2


# Fed from EXECUTOR threads (the LogManager flush loop accounts append
# bytes off-loop; snapshot commits run in the executor) as well as the
# store's event loop — cross-thread like DiskLatencyProbe, so it
# carries its own lock.
class DiskBudget:
    """Per-store disk usage estimate -> hysteretic {OK, NEAR_FULL,
    FULL} pressure.

    Hot-path fed like the HealthTracker (the PR 11 lesson: signals the
    hot path already produces, measured where they happen): log-append
    bytes, snapshot commit/prune deltas, journal-compaction reclaim —
    plus a periodic ``reconcile()`` against real directory/statvfs
    usage that re-bases the estimate (rmtree-style deletes and native
    journal GC never report through the hot path)."""

    def __init__(self, opts: DiskBudgetOptions | None = None,
                 label: str = ""):
        self.opts = opts or DiskBudgetOptions()
        self.label = label
        self._lock = threading.Lock()
        self._base = 0             # reconciled usage      guarded-by: _lock
        self._delta = 0            # hot-path bytes since  guarded-by: _lock
        self._capacity = int(self.opts.budget_bytes)  # guarded-by: _lock
        self._enospc_latch = 0     # rounds pinned FULL    guarded-by: _lock
        self._hyst = _Hysteresis(self.opts.worsen_after,
                                 self.opts.recover_after,
                                 levels=_PRESSURE_LEVELS,
                                 initial=PRESSURE_OK)  # guarded-by: _lock
        # observability (all guarded-by: _lock)
        self.evaluations = 0
        self.reconciles = 0
        self.enospc_events = 0
        self.appended_bytes = 0
        self.reclaimed_bytes = 0
        self.full_rounds = 0
        self.near_full_rounds = 0
        self.resumes = 0           # FULL -> better transitions

    # -- signal intake (hot paths, any thread) -------------------------------

    def note_append(self, nbytes: int) -> None:
        """Log bytes flushed to storage (LogManager flush loop)."""
        with self._lock:
            self._delta += nbytes
            self.appended_bytes += nbytes

    def note_snapshot(self, delta_bytes: int) -> None:
        """Snapshot commit (+bytes) or prune/delete (-bytes)."""
        with self._lock:
            self._delta += delta_bytes
            if delta_bytes < 0:
                self.reclaimed_bytes += -delta_bytes

    def note_reclaimed(self, nbytes: int) -> None:
        """Bytes freed by log/journal compaction."""
        with self._lock:
            self._delta -= nbytes
            self.reclaimed_bytes += nbytes

    def note_enospc(self) -> None:
        """The disk itself refused a write: pin raw FULL for the next
        ``enospc_latch_rounds`` evaluations whatever the estimate says
        — the estimate is wrong, the errno is not."""
        with self._lock:
            self.enospc_events += 1
            self._enospc_latch = max(self._enospc_latch,
                                     self.opts.enospc_latch_rounds)

    def set_budget(self, budget_bytes: int) -> None:
        """Operator resize: adopt a new explicit byte ceiling mid-run
        (volume grown/shrunk under the store).  0 switches to the
        reconcile-reported capacity (statvfs mode)."""
        with self._lock:
            self.opts.budget_bytes = int(budget_bytes)
            if budget_bytes > 0:
                self._capacity = int(budget_bytes)

    def reconcile(self, used_bytes: int,
                  capacity_bytes: int | None = None) -> None:
        """Re-base the estimate on measured usage (directory walk or
        statvfs, taken OFF the hot path by the store's health task)."""
        with self._lock:
            self._base = max(0, int(used_bytes))
            self._delta = 0
            if self.opts.budget_bytes <= 0 and capacity_bytes:
                self._capacity = int(capacity_bytes)
            self.reconciles += 1

    # -- scoring -------------------------------------------------------------

    def used_bytes(self) -> int:
        with self._lock:
            return max(0, self._base + self._delta)

    def capacity_bytes(self) -> int:
        with self._lock:
            return self._capacity

    def pressure(self) -> str:
        """Current hysteretic pressure (no new evaluation round)."""
        with self._lock:
            return self._hyst.level

    def evaluate(self) -> str:
        """One pressure round (the store's health task cadence): fold
        the usage estimate — or the ENOSPC latch — through the
        thresholds and the hysteresis; records flight-recorder
        ``disk_pressure`` events on transitions."""
        from tpuraft.util.trace import RECORDER

        with self._lock:
            used = max(0, self._base + self._delta)
            cap = self._capacity
            if self._enospc_latch > 0:
                self._enospc_latch -= 1
                raw = PRESSURE_FULL
            elif cap <= 0:
                raw = PRESSURE_OK
            elif used >= cap * self.opts.full_frac:
                raw = PRESSURE_FULL
            elif used >= cap * self.opts.near_full_frac:
                raw = PRESSURE_NEAR_FULL
            else:
                raw = PRESSURE_OK
            prev = self._hyst.level
            level = self._hyst.fold(raw)
            self.evaluations += 1
            if level == PRESSURE_FULL:
                self.full_rounds += 1
            elif level == PRESSURE_NEAR_FULL:
                self.near_full_rounds += 1
            if prev == PRESSURE_FULL and level != PRESSURE_FULL:
                self.resumes += 1
        if level != prev:
            RECORDER.record("disk_pressure", self.label,
                            level=level, was=prev, used=used, capacity=cap)
            if level == PRESSURE_FULL:
                RECORDER.note_anomaly(
                    "disk_full",
                    f"{self.label or 'store'}: {used}/{cap} bytes "
                    f"(+{self.enospc_events} enospc)")
        return level

    # -- observability -------------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return {
                "disk_pressure_level": _PRESSURE_LEVELS[self._hyst.level],
                "disk_used_bytes": max(0, self._base + self._delta),
                "disk_capacity_bytes": self._capacity,
                "disk_enospc_events": self.enospc_events,
                "disk_appended_bytes": self.appended_bytes,
                "disk_reclaimed_bytes": self.reclaimed_bytes,
                "disk_reconciles": self.reconciles,
                "disk_full_rounds": self.full_rounds,
                "disk_near_full_rounds": self.near_full_rounds,
                "disk_pressure_resumes": self.resumes,
            }

    def register_gauges(self, metrics) -> None:
        metrics.gauge("disk.pressure_level",
                      lambda: float(_PRESSURE_LEVELS[self.pressure()]))
        metrics.gauge("disk.used_bytes", lambda: float(self.used_bytes()))
        metrics.gauge("disk.capacity_bytes",
                      lambda: float(self.capacity_bytes()))
        metrics.gauge("disk.enospc_events",
                      lambda: float(self.enospc_events))

    def describe(self) -> str:
        with self._lock:
            used = max(0, self._base + self._delta)
            return (f"DiskBudget<{self._hyst.level} used={used} "
                    f"cap={self._capacity} enospc={self.enospc_events} "
                    f"appended={self.appended_bytes} "
                    f"reclaimed={self.reclaimed_bytes} "
                    f"reconciles={self.reconciles} resumes={self.resumes}>")
