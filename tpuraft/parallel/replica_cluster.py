"""Bootable replica-plane cluster (VERDICT r2 #2): the deployment mode
that wires R co-located replica endpoints x G raft groups onto ONE
:class:`tpuraft.parallel.replica_plane.ReplicatedClusterPlane` — every
node's ballot box is a row-view of the [R, G] collective commit plane,
so the quorum commit point for ALL groups is one replica-axis
all_gather + order statistic per tick (reference role: the NCCL/MPI
"math plane" of ``core:ReplicatorGroup`` ack aggregation, redesigned as
an XLA collective over the device mesh — SURVEY.md §6 comms backend).

This is package code an operator can boot (``examples/replica_plane.py``
is the runnable main); the test suite and the driver's multi-chip dry
run consume THIS class rather than a test-only harness.

Topology: each replica endpoint hosts one replica of every group behind
one RpcServer/NodeManager; entries still travel the protocol plane
(AppendEntries RPC), while commit advancement comes from each replica's
own DURABLE log state via the plane's ``on_stable`` hook — see
replica_plane.py's term-scoped-attestation safety note.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from tpuraft.conf import Configuration
from tpuraft.core.node import Node, State
from tpuraft.core.node_manager import NodeManager
from tpuraft.core.state_machine import Iterator, StateMachine
from tpuraft.entity import PeerId, Task
from tpuraft.options import NodeOptions
from tpuraft.parallel.replica_plane import ReplicatedClusterPlane
from tpuraft.rpc.transport import InProcNetwork, InProcTransport, RpcServer


class RecordingStateMachine(StateMachine):
    """Minimal FSM for examples/bring-up: records applied payloads."""

    def __init__(self):
        self.logs: list[bytes] = []

    async def on_apply(self, it: Iterator) -> None:
        while it.valid():
            self.logs.append(it.data())
            it.next()


class ReplicaPlaneCluster:
    """R replica endpoints x G groups over ONE ReplicatedClusterPlane.

    Parameters
    ----------
    fsm_factory: called as ``fsm_factory()`` per (group, replica) node;
        defaults to :class:`RecordingStateMachine`.
    log_uri / meta_uri: per-node storage URIs; ``{group}`` and
        ``{replica}`` placeholders are substituted, so
        ``multilog:///data/r{replica}#{group}`` gives each replica one
        shared journal engine across its groups.
    mesh: optional 2D ``jax.sharding.Mesh`` with ("replica", "groups")
        axes; None runs the plane's numpy twin (tiny deployments).
    net: optional shared InProcNetwork (tests inject one to partition
        endpoints); by default a fresh loopback network is created.
    transport: "inproc" (default), "tcp" (asyncio loopback sockets) or
        "native" (C++ epoll engine) — the protocol plane above the
        replica-axis collective is transport-pluggable like the rest of
        the stack (VERDICT r3 #8); co-location of the REPLICA plane is
        inherent (one jax process), but its RPC traffic can ride real
        sockets.
    """

    def __init__(self, n_replicas: int, n_groups: int, mesh=None,
                 election_timeout_ms: int = 400,
                 fsm_factory: Optional[Callable[[], StateMachine]] = None,
                 log_uri: str = "memory://", meta_uri: str = "memory://",
                 base_port: int = 7700, tick_interval_ms: int = 5,
                 net: Optional[InProcNetwork] = None,
                 transport: str = "inproc"):
        if transport not in ("inproc", "tcp", "native"):
            raise ValueError(f"unknown transport {transport!r}")
        self.transport_kind = transport
        self._servers: list = []
        self._transports: list = []
        self.net = net or InProcNetwork()
        self.R = n_replicas
        self.endpoints = [PeerId.parse(f"127.0.0.1:{base_port + i}")
                          for i in range(n_replicas)]
        self.conf = Configuration(list(self.endpoints))
        self.groups = [f"g{k}" for k in range(n_groups)]
        self.plane = ReplicatedClusterPlane(
            n_replicas, n_groups, mesh=mesh,
            tick_interval_ms=tick_interval_ms)
        self.nodes: dict[tuple[str, PeerId], Node] = {}
        self.fsms: dict[tuple[str, PeerId], StateMachine] = {}
        self.election_timeout_ms = election_timeout_ms
        self._fsm_factory = fsm_factory or RecordingStateMachine
        self._log_uri = log_uri
        self._meta_uri = meta_uri

    def _uri(self, template: str, gid: str, replica: int) -> str:
        return template.format(group=gid, replica=replica)

    async def _make_endpoint(self, ep: PeerId):
        """One (server, transport) pair per replica endpoint, by kind."""
        if self.transport_kind == "tcp":
            from tpuraft.rpc.tcp import TcpRpcServer, TcpTransport

            server = TcpRpcServer(ep.endpoint)
            await server.start()
            transport = TcpTransport(endpoint=ep.endpoint)
        elif self.transport_kind == "native":
            from tpuraft.rpc.native_tcp import (NativeTcpRpcServer,
                                                NativeTcpTransport)

            server = NativeTcpRpcServer(ep.endpoint)
            await server.start()
            transport = NativeTcpTransport(endpoint=ep.endpoint)
        else:
            server = RpcServer(ep.endpoint)
            self.net.bind(server)
            transport = InProcTransport(self.net, ep.endpoint)
        self._servers.append(server)
        self._transports.append(transport)
        return server, transport

    async def start_all(self) -> None:
        await self.plane.start()
        for r, ep in enumerate(self.endpoints):
            server, transport = await self._make_endpoint(ep)
            manager = NodeManager(server)
            for gid in self.groups:
                fsm = self._fsm_factory()
                self.fsms[(gid, ep)] = fsm
                opts = NodeOptions(
                    election_timeout_ms=self.election_timeout_ms,
                    initial_conf=self.conf.copy(), fsm=fsm,
                    log_uri=self._uri(self._log_uri, gid, r),
                    raft_meta_uri=self._uri(self._meta_uri, gid, r))
                node = Node(gid, ep, opts, transport,
                            ballot_box_factory=self.plane.ballot_box_factory(
                                gid, r))
                node.node_manager = manager
                manager.add(node)
                if not await node.init():
                    raise RuntimeError(f"node init failed: {gid}@{ep}")
                self.nodes[(gid, ep)] = node

    async def stop_all(self) -> None:
        for node in self.nodes.values():
            await node.shutdown()
        for t in self._transports:
            close = getattr(t, "close", None)
            if close is not None:
                await close()
        for s in self._servers:
            stop = getattr(s, "stop", None)
            if stop is not None:
                await stop()
        await self.plane.shutdown()

    async def stop_replica(self, ep: PeerId) -> None:
        """Crash one replica endpoint: silence its network and shut its
        nodes down (chaos hook for examples/tests)."""
        if self.transport_kind == "inproc":
            self.net.stop_endpoint(ep.endpoint)
        else:
            i = self.endpoints.index(ep)
            await self._servers[i].stop()
        for key in [k for k in self.nodes if k[1] == ep]:
            await self.nodes.pop(key).shutdown()

    async def wait_leader(self, gid: str, timeout_s: float = 10.0) -> Node:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while loop.time() < deadline:
            leaders = [n for (g, ep), n in self.nodes.items()
                       if g == gid and n.state == State.LEADER]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise TimeoutError(f"no leader for {gid}")

    async def apply_ok(self, node: Node, data: bytes,
                       timeout_s: float = 10.0):
        fut = asyncio.get_running_loop().create_future()
        await node.apply(Task(data=data, done=fut.set_result))
        st = await asyncio.wait_for(fut, timeout_s)
        if not st.is_ok():
            raise RuntimeError(f"apply failed: {st}")
        return st
