"""Group-axis sharding of the multi-raft tick over a device mesh.

The tick kernel (tpuraft.ops.tick) is element-wise over the G axis, so
sharding G over the mesh makes every chip advance its shard of raft
groups with zero cross-chip traffic; cross-chip collectives only appear
in (a) global metrics reductions and (b) the replica-axis quorum plane
(tpuraft.parallel.collective).  This mirrors how the reference scales:
thousands of independent groups per process, processes scaled out
(SURVEY.md §3.5 row 1).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuraft.ops.tick import GroupState, TickOutputs, TickParams, raft_tick


def make_mesh(n_devices: Optional[int] = None, axis_name: str = "groups"
              ) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(
            f"mesh of {n} devices requested but only {len(devs)} present "
            f"({devs[0].platform}) — a silent truncation would change the "
            f"sharding the caller validated against")
    return Mesh(np.array(devs[:n]), (axis_name,))


def shard_group_state(state: GroupState, mesh: Mesh, axis_name: str = "groups"
                      ) -> GroupState:
    """Place the SoA state with G sharded over the mesh.  G must divide the
    mesh size evenly (pad the group capacity, not the mesh)."""

    def put(x):
        spec = P(axis_name, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, state)


def group_shardings(mesh: Mesh, axis_name: str = "groups"
                    ) -> tuple[NamedSharding, NamedSharding]:
    """(vector, matrix) shardings over the group axis: ``[G]`` fields get
    the first, ``[G, P]`` fields the second.  The single home for the
    group-axis layout — the engine and tick compilers both use it."""
    return (NamedSharding(mesh, P(axis_name)),
            NamedSharding(mesh, P(axis_name, None)))


def sharded_tick(mesh: Mesh, axis_name: str = "groups", donate: bool = True):
    """Compile raft_tick with G sharded over the mesh.  Returns the jitted
    function; call with (state, now_ms, params)."""
    row, mat = group_shardings(mesh, axis_name)
    scalar = NamedSharding(mesh, P())

    def state_shardings(state_cls=GroupState):
        # all [G] fields -> row, all [G,P] fields -> mat
        return GroupState(
            role=row, commit_rel=row, pending_rel=row, match_rel=mat,
            granted=mat, voter_mask=mat, old_voter_mask=mat,
            elect_deadline=row, hb_deadline=row, last_ack=mat,
            snap_deadline=row, quiescent=row, witness_mask=mat,
            stepdown_deadline=row, fence_start=row)

    out_outputs = TickOutputs(
        commit_rel=row, commit_advanced=row, elected=row, election_due=row,
        step_down=row, hb_due=row, lease_valid=row, snap_due=row, q_ack=row,
        stepdown_due=row, fence_ok=row)
    params_sharding = TickParams(scalar, scalar, scalar, scalar)
    return jax.jit(
        raft_tick,
        in_shardings=(state_shardings(), scalar, params_sharding),
        out_shardings=(state_shardings(), out_outputs),
        donate_argnums=(0,) if donate else (),
    )


# deadline-fold sentinel: "no engine-scheduled deadline on this shard" —
# int32 max, NOT the engine's 1<<60 host sentinel (the fold runs in the
# device's int32 time domain)
DEADLINE_NONE_I32 = np.int32(2**31 - 1)


def sharded_deadline_fold(mesh: Mesh, axis_name: str = "groups"):
    """Compile the engine's earliest-deadline scan as ONE sharded
    reduction: each device folds its own group rows (election deadlines
    for awake followers/candidates, heartbeat + stepdown deadlines for
    awake leaders) and a single collective min produces the scalar the
    tick loop sleeps toward.  The host-side numpy equivalent
    (MultiRaftEngine._next_deadline) would gather every sharded row back
    to host per loop iteration — the exact per-iteration sync the mesh
    mode exists to avoid.

    Returns a jitted fn: (role, quiescent, has_ctrl, elect_deadline,
    hb_deadline, stepdown_deadline) int32 [G] rows -> int32 scalar
    (DEADLINE_NONE_I32 when no slot schedules anything).
    """
    row = NamedSharding(mesh, P(axis_name))
    scalar = NamedSharding(mesh, P())

    def fold(role, quiescent, has_ctrl, elect_deadline, hb_deadline,
             stepdown_deadline):
        awake = has_ctrl & ~quiescent
        # ROLE_FOLLOWER == 0, ROLE_CANDIDATE == 1, ROLE_LEADER == 2
        ec = awake & (role <= 1)
        ld = awake & (role == 2)
        none = jnp.int32(DEADLINE_NONE_I32)
        nxt = jnp.min(jnp.where(ec, elect_deadline, none))
        nxt = jnp.minimum(nxt, jnp.min(jnp.where(ld, hb_deadline, none)))
        nxt = jnp.minimum(
            nxt, jnp.min(jnp.where(ld, stepdown_deadline, none)))
        return nxt

    return jax.jit(fold, in_shardings=(row,) * 6, out_shardings=scalar)
