"""Replica-axis collective commit plane: a deployment mode where the
co-located replicas of MANY raft groups sit on a 2D (replica, groups)
device mesh and the quorum commit point is computed by XLA collectives
(tpuraft.parallel.collective.replicated_tick: all_gather over the
replica axis + q-th order statistic) from each replica's DURABLE
protocol state — the BASELINE.json config-4 north star ("vote-matrix
psum over ICI"), promoted from a dry-run demo to a runtime path
(VERDICT r1 #6).

Data flow per replica r of group g:
  LogManager flush fsyncs entries -> on_stable hook ->
  plane.match[r, g] = last durable index        (host -> device row)
  plane tick: commit[g] = q-th largest over the replica axis (ICI)
  leader's ReplicaBallotBox._advance(commit[g]) -> FSMCaller

Contrast with the [G, P] MultiRaftEngine plane: there, the LEADER owns a
row of acked matchIndexes that followers ECHO back over RPC; here each
replica's own durability directly feeds the reduce and no ack echo is
needed for commit advancement — the protocol plane (AppendEntries over
host RPC) still ships the entries themselves and the leader heartbeats.

SAFETY — term-scoped attestation.  A replica's raw durable tip may
include a DIVERGENT suffix from a deposed leader (raft only lets
matchIndex advance through verified AppendEntries consistency).
Counting such a row would commit entries a quorum does not actually
hold.  A row therefore counts toward leader T's quorum only while the
replica is ATTESTED to T: the replica sets accepted_term[r,g] = T
exactly when it locally knows its whole log prefix-matches T's (an
accepted append that covered its tail, or a heartbeat at its tail), and
zeroes it the moment an append from any other term touches its log.
The tick masks unattested rows to 0 before the collective reduce.
Once attested, every further durable advance IS a T-append, so the row
stays valid until the next term change.

Scope: commit advancement for symmetric R-replica groups.  Votes and
joint-consensus quorums stay on the protocol plane: a [R, G] grant
matrix cannot attribute grants to one of several concurrent candidates
(grants are per (term, candidate)), and joint consensus needs two
asymmetric voter sets — both are per-candidate/per-conf slow paths, not
the steady-state commit stream this plane accelerates.

On real hardware, each host of the mesh holds its replica's rows and
the collectives ride ICI; in one process (tests, the driver dry run) a
CPU mesh stands in, same program.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

import numpy as np

from tpuraft.conf import Configuration
from tpuraft.entity import PeerId

LOG = logging.getLogger(__name__)

_REBASE_LIMIT = 1 << 28


class ReplicaBallotBox:
    """BallotBox SPI over the plane: commit quorum = the collective
    reduce of durable replica rows (not echoed acks)."""

    def __init__(self, plane: "ReplicatedClusterPlane", replica: int,
                 slot: int, on_committed: Callable[[int], None]):
        self._plane = plane
        self.replica = replica
        self.slot = slot
        self._on_committed = on_committed
        self.last_committed_index = 0
        self.pending_index = 0

    # -- wiring (Node.init) --------------------------------------------------

    def attach_log_manager(self, log_manager) -> None:
        plane, r, s = self._plane, self.replica, self.slot

        def on_stable(index: int) -> None:
            # EXACT-tip semantics (not monotone max): suffix truncation
            # and InstallSnapshot resets LOWER the durable tip, and a
            # stale-high row would count dropped entries toward a quorum
            if index != plane.match[r, s]:
                plane.match[r, s] = index
                plane.mark_dirty()

        log_manager.on_stable = on_stable
        # recovered logs count as durable immediately
        on_stable(log_manager.last_log_index())

    # -- attestation (see module docstring SAFETY) ---------------------------

    def note_append_start(self, term: int) -> None:
        """An append from `term` is about to mutate this replica's log:
        if that changes leadership lineage, the old attestation dies NOW
        (before any on_stable can advance the row with foreign entries)."""
        p = self._plane
        if p.accepted_term[self.replica, self.slot] != term:
            p.accepted_term[self.replica, self.slot] = 0

    def note_attested(self, term: int) -> None:
        """This replica locally verified its whole log prefix-matches
        leader `term`'s log (append covered the tail / heartbeat at
        tail / is the leader itself)."""
        self._plane.accepted_term[self.replica, self.slot] = term
        self._plane.mark_dirty()

    # -- leader side ---------------------------------------------------------

    def reset_pending_index(self, new_pending_index: int) -> None:
        p = self._plane
        self.pending_index = new_pending_index
        p.leader_replica[self.slot] = self.replica
        p.base[self.slot] = new_pending_index - 1
        p.commit_abs[self.slot] = new_pending_index - 1
        p.mark_dirty()

    def clear_pending(self) -> None:
        self.pending_index = 0
        p = self._plane
        if p.leader_replica[self.slot] == self.replica:
            p.leader_replica[self.slot] = -1

    def commit_at(self, peer: PeerId, match_index: int, conf: Configuration,
                  old_conf: Configuration) -> bool:
        """Remote ack echoes are redundant here: the remote replica's own
        on_stable already fed its row.  Self-acks land the same way."""
        return False

    def update_conf(self, conf: Configuration, old_conf: Configuration) -> None:
        n = len(conf.peers)
        if not old_conf.is_empty() or (n and n != self._plane.R):
            raise ValueError(
                "ReplicatedClusterPlane serves symmetric R-replica groups; "
                "joint consensus / resizing needs the [G,P] engine plane "
                f"(conf={conf}, old={old_conf}, R={self._plane.R})")

    def close(self) -> None:
        self._plane.release(self)

    # -- follower side -------------------------------------------------------

    def set_last_committed_index(self, index: int) -> bool:
        if self.pending_index != 0:
            return False
        if index <= self.last_committed_index:
            return False
        self.last_committed_index = index
        self._on_committed(index)
        return True

    # plane callback
    def _advance(self, new_commit: int) -> None:
        if self.pending_index == 0:
            return
        if new_commit > self.last_committed_index:
            self.last_committed_index = new_commit
            self._on_committed(new_commit)


class ReplicatedClusterPlane:
    """One per process (or per mesh-driving host): [R, G] durable-match
    and grant planes reduced by replica-axis collectives per tick."""

    def __init__(self, n_replicas: int, max_groups: int,
                 mesh=None, tick_interval_ms: int = 10):
        self.R = n_replicas
        self.G = max_groups
        self.mesh = mesh
        self.tick_interval_ms = tick_interval_ms
        self.match = np.zeros((self.R, self.G), np.int64)
        self.accepted_term = np.zeros((self.R, self.G), np.int64)
        self.base = np.zeros(self.G, np.int64)
        self.commit_abs = np.zeros(self.G, np.int64)
        self.leader_replica = np.full(self.G, -1, np.int32)
        self._boxes: dict[tuple[int, int], ReplicaBallotBox] = {}
        self._slot_of: dict[str, int] = {}
        self._next_slot = 0
        self._fn = None
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._dirty = False
        self._dirty_event = asyncio.Event()
        self.ticks = 0
        self.commit_advances = 0

    # -- registry ------------------------------------------------------------

    def slot_for(self, group_id: str) -> int:
        s = self._slot_of.get(group_id)
        if s is None:
            if self._next_slot >= self.G:
                raise RuntimeError(f"plane full: {self.G} groups")
            s = self._slot_of[group_id] = self._next_slot
            self._next_slot += 1
        return s

    def ballot_box_factory(self, group_id: str, replica: int):
        """Factory for Node(ballot_box_factory=...): one per (group,
        replica).  The replica index is this node's row."""

        def make(on_committed: Callable[[int], None]) -> ReplicaBallotBox:
            slot = self.slot_for(group_id)
            box = ReplicaBallotBox(self, replica, slot, on_committed)
            self._boxes[(replica, slot)] = box
            return box

        return make

    def release(self, box: ReplicaBallotBox) -> None:
        self._boxes.pop((box.replica, box.slot), None)
        self.match[box.replica, box.slot] = 0
        self.accepted_term[box.replica, box.slot] = 0

    def mark_dirty(self) -> None:
        self._dirty = True
        self._dirty_event.set()

    # -- tick loop -----------------------------------------------------------

    async def start(self) -> None:
        if self.mesh is not None:
            from tpuraft.parallel.collective import replicated_tick

            self._fn = replicated_tick(self.mesh, self.R)
            self.tick_once()  # warm the compile before protocol traffic
        self._task = asyncio.ensure_future(self._loop())

    async def shutdown(self) -> None:
        self._stopped = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        interval = self.tick_interval_ms / 1000.0
        while not self._stopped:
            if not self._dirty:
                self._dirty_event.clear()
                try:
                    await asyncio.wait_for(self._dirty_event.wait(), interval)
                except asyncio.TimeoutError:
                    continue
            self._dirty = False
            t0 = time.perf_counter()
            try:
                self.tick_once()
            except Exception:
                LOG.exception("replica plane tick failed")
                self._dirty = True
            await asyncio.sleep(
                max(0.001, (time.perf_counter() - t0) * 0.5))

    def _rebase(self) -> None:
        hot = (self.match.max(axis=0) - self.base) > _REBASE_LIMIT
        for s in np.nonzero(hot)[0]:
            self.base[s] = self.commit_abs[s]

    def tick_once(self) -> int:
        """One collective commit reduction across all groups."""
        self._rebase()
        rel = np.clip(self.match - self.base[None, :], 0, None
                      ).astype(np.int32)
        # SAFETY mask: a row only counts toward the quorum while its
        # replica is attested to the group's CURRENT leader lineage
        # (leader's own accepted_term == its current term)
        lead = self.leader_replica
        lt = np.where(
            lead >= 0,
            self.accepted_term[lead.clip(0), np.arange(self.G)], -1)
        attested = (self.accepted_term == lt[None, :]) & (lt[None, :] > 0)
        rel = np.where(attested, rel, 0)
        if self._fn is not None:
            import jax.numpy as jnp

            commit_rel, _votes = self._fn(
                jnp.asarray(rel),
                jnp.zeros((self.R, self.G), bool))
            commit_rel = np.asarray(commit_rel)
        else:  # numpy oracle (no mesh): q-th largest over replicas
            q = self.R // 2 + 1
            commit_rel = np.sort(rel, axis=0)[::-1][q - 1]
        self.ticks += 1
        advanced = 0
        commit_abs = self.base + commit_rel
        for s in np.nonzero(commit_abs > self.commit_abs)[0]:
            lr = self.leader_replica[s]
            if lr < 0:
                continue
            box = self._boxes.get((int(lr), int(s)))
            if box is None or box.pending_index == 0:
                continue
            new_commit = int(commit_abs[s])
            # Raft §5.4.2: only entries of the CURRENT leadership commit
            # via quorum counting — the plane's pending baseline
            if new_commit < box.pending_index:
                continue
            self.commit_abs[s] = new_commit
            box._advance(new_commit)
            advanced += 1
        self.commit_advances += advanced
        return advanced

    def describe(self) -> str:
        return (f"ReplicatedClusterPlane<R={self.R} G={self.G} "
                f"groups={self._next_slot} mesh={self.mesh is not None} "
                f"ticks={self.ticks} advances={self.commit_advances}>")
