"""Replica-axis quorum plane: XLA collectives over ICI as the vote fabric.

The BASELINE.json north-star configuration ("16K groups, 3 replicas —
vote-matrix psum over v5e-8 ICI mesh"): each slice of the mesh's
``replica`` axis holds one raft replica's LOCAL view of all G groups
(its matchIndex row, its vote).  Quorum math then rides ICI:

- vote counting   = ``psum`` of grant indicators over the replica axis;
- commit point    = ``all_gather`` of match rows over the replica axis,
  then the q-th order statistic — the [G, P] matrix never exists on any
  single chip until the gather, and XLA pipelines the gather with the sort.

This is the TPU-native analog of the reference's NCCL-free Bolt RPC vote
traffic (SURVEY.md §6): the protocol plane (host RPC over DCN) establishes
*what* each replica has durably; the math plane reduces it over ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

def replica_vote_count(granted_block: jnp.ndarray,
                       axis_name: str = "replica") -> jnp.ndarray:
    """Inside shard_map: granted_block bool [R_local, G_local] are this
    mesh slice's replicas' grants; returns votes int32 [1, G_local] =
    total granting replicas across the axis."""
    local = granted_block.astype(jnp.int32).sum(axis=0, keepdims=True)
    return jax.lax.psum(local, axis_name)


def replica_commit_point(match_block: jnp.ndarray, n_replicas: int,
                         axis_name: str = "replica") -> jnp.ndarray:
    """Inside shard_map: match_block int32 [R_local, G_local] holds this
    slice's replicas' durable matchIndex rows; returns the quorum commit
    point [1, G_local] (q-th largest across all replicas, q = n//2+1)."""
    gathered = jax.lax.all_gather(match_block, axis_name, axis=0,
                                  tiled=True)  # [R, G_local]
    sorted_desc = -jnp.sort(-gathered, axis=0)
    q = n_replicas // 2 + 1
    return sorted_desc[q - 1][None, :]


def replicated_tick(mesh: Mesh, n_replicas: int,
                    replica_axis: str = "replica",
                    group_axis: str = "groups"):
    """Build the jitted cross-replica quorum step over a 2D mesh
    (replica, groups).

    Inputs (global shapes):
      match:   int32 [R, G]  — row r = replica r's durable matchIndex
      granted: bool  [R, G]  — row r = replica r's current-election vote
    Outputs (global):
      commit:  int32 [G] — quorum commit point per group
      votes:   int32 [G] — vote counts per group
    """
    # jax moved shard_map out of experimental and renamed check_rep ->
    # check_vma after 0.4.x — as SEPARATE changes, so feature-detect the
    # kwarg from the signature rather than keying it off where shard_map
    # lives (a public jax.shard_map may still take check_rep)
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:
        from jax.experimental.shard_map import shard_map  # jax <= 0.4.x
    import inspect

    try:
        params = inspect.signature(shard_map).parameters
    except (TypeError, ValueError):  # builtins/partials without signatures
        params = {}
    check_kw = {"check_rep": False} if "check_rep" in params \
        else {"check_vma": False}

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(replica_axis, group_axis), P(replica_axis, group_axis)),
        out_specs=(P(None, group_axis), P(None, group_axis)),
        # outputs ARE replica-identical (post-psum/gather)
        **check_kw,
    )
    def step(match_block, granted_block):
        # blocks: [R_local, G_local]; local rows fold first, then the
        # collectives ride the replica axis (ICI on hardware)
        commit = replica_commit_point(match_block, n_replicas, replica_axis)
        votes = replica_vote_count(granted_block, replica_axis)
        return commit, votes

    def run(match: jnp.ndarray, granted: jnp.ndarray):
        commit, votes = step(match, granted)
        return commit[0], votes[0]

    return jax.jit(run)
