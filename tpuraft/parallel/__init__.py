"""Device-mesh parallelism for the multi-raft tick.

Two complementary planes (SURVEY.md §6 "Distributed communication
backend", BASELINE.json north star):

- :mod:`tpuraft.parallel.mesh` — shard the ``[G, P]`` group-state tensors
  over the mesh's ``groups`` axis (multi-group data parallelism, the
  reference's NodeManager/RegionEngine axis vectorized);
- :mod:`tpuraft.parallel.collective` — quorum math where each mesh slice
  along the ``replica`` axis holds one replica's local view: vote counting
  via ``psum`` and commit points via ``all_gather`` + order statistic over
  ICI (the "vote-matrix psum" configuration).
"""

from tpuraft.parallel.mesh import make_mesh, shard_group_state, sharded_tick
from tpuraft.parallel.collective import (
    replica_commit_point,
    replica_vote_count,
    replicated_tick,
)

__all__ = [
    "make_mesh",
    "shard_group_state",
    "sharded_tick",
    "replica_commit_point",
    "replica_vote_count",
    "replicated_tick",
]
