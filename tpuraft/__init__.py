"""tpuraft — a TPU-native multi-raft consensus framework.

Re-imagines the capabilities of SOFAJRaft (reference: finalcola/sofa-jraft)
for TPU hardware: thousands of independent Raft groups' quorum math (ballot
counting, commitIndex advancement, election/lease checks) run as one
vectorized JAX/XLA kernel over ``[groups, peers]`` state tensors, sharded
over a device mesh with ``jax.sharding`` — while an asyncio host runtime
implements the protocol envelope (RPC, timers, log management, snapshots,
membership change) and a native C++ layer provides durable log storage.

Layer map (mirrors SURVEY.md §2):
  L1 runtime utils      tpuraft.util
  L2 RPC / transport    tpuraft.rpc
  L3 storage            tpuraft.storage
  L4 consensus core     tpuraft.core  (+ device plane in tpuraft.ops)
  L5 client & routing   tpuraft.client
  L6 RheaKV store       tpuraft.rhea
  L7 examples           examples/
"""

__version__ = "0.1.0"

from tpuraft.errors import RaftError, Status
from tpuraft.entity import PeerId, LogId, LogEntry, EntryType, Task
from tpuraft.conf import Configuration

__all__ = [
    "RaftError",
    "Status",
    "PeerId",
    "LogId",
    "LogEntry",
    "EntryType",
    "Task",
    "Configuration",
]
