"""Device plane: vectorized consensus math as pure JAX functions.

This package replaces the reference's per-group hot-path objects —
``core:core/BallotBox`` (per-index Ballot quorum counting), the matchIndex
side of ``core:core/Replicator``, and election vote counting in
``core:core/NodeImpl`` — with one set of kernels over ``[G, P]`` tensors
(G raft groups x P peer slots), designed for the MXU/VPU and for sharding
over a TPU mesh (SURVEY.md §8).

Key reformulation: per-index ballots within the pending window are
equivalent to an order statistic — the index committed by a quorum of q
voters is the q-th largest matchIndex (proof sketch: matchIndex_p >= i
means peer p acked every index <= i, so |{p: match_p >= i}| >= q iff
i <= qth_largest(match)).  Joint consensus (old+new conf) takes the min of
the two order statistics.  All indexes on device are int32 *relative to a
per-group host-managed base* so unbounded log indexes never hit the device.
"""

from tpuraft.ops.ballot import (
    quorum_match_index,
    joint_quorum_match_index,
    vote_quorum,
    NEG_INF_I32,
)
from tpuraft.ops.tick import GroupState, TickParams, TickOutputs, raft_tick

__all__ = [
    "quorum_match_index",
    "joint_quorum_match_index",
    "vote_quorum",
    "NEG_INF_I32",
    "GroupState",
    "TickParams",
    "TickOutputs",
    "raft_tick",
]
