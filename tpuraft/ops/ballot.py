"""Ballot/quorum kernels: the vectorized BallotBox.

Replaces the reference's ``core:core/BallotBox#commitAt`` / ``Ballot#grant``
per-index loop (SURVEY.md §4.2 hot path) with order statistics over the
``[G, P]`` matchIndex matrix, and election tallying in
``core:core/NodeImpl#handleRequestVoteResponse`` with a masked popcount.

Everything is pure jnp — jit/vmap/shard_map friendly, no data-dependent
shapes.  P (peer slots) is small (<= 16 in practice); a full sort along the
last axis lowers to an O(P log P) sorting network on the VPU, negligible
against the [G]-axis parallelism.
"""

from __future__ import annotations

import jax.numpy as jnp

# Sentinel for masked-out peer slots. Using iinfo.min would overflow under
# arithmetic; half-range is safely below any valid relative index (>= -1).
NEG_INF_I32 = jnp.int32(-(2**30))


def _masked_desc_sort(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Sort each row descending with masked slots pushed to the end."""
    v = jnp.where(mask, values.astype(jnp.int32), NEG_INF_I32)
    return -jnp.sort(-v, axis=-1)


def quorum_match_index(match: jnp.ndarray, voter_mask: jnp.ndarray) -> jnp.ndarray:
    """Per-group largest index replicated on a quorum of voters.

    match: int32 [..., P] relative matchIndex per peer slot (leader's own
      slot must contain its lastLogIndex).
    voter_mask: bool [..., P] — True for slots that are voters in the
      current configuration.

    Returns int32 [...]: the q-th largest matchIndex among voters, where
    q = floor(n_voters/2) + 1; NEG_INF_I32 for groups with zero voters.
    """
    sorted_desc = _masked_desc_sort(match, voter_mask)
    n_voters = voter_mask.sum(axis=-1).astype(jnp.int32)
    quorum = n_voters // 2 + 1
    q_idx = jnp.clip(quorum - 1, 0, match.shape[-1] - 1)
    picked = jnp.take_along_axis(sorted_desc, q_idx[..., None], axis=-1)[..., 0]
    return jnp.where(n_voters > 0, picked, NEG_INF_I32)


def joint_quorum_match_index(
    match: jnp.ndarray,
    voter_mask: jnp.ndarray,
    old_voter_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Joint-consensus commit point: needs a quorum of BOTH configurations.

    Groups not in joint mode should pass an all-False ``old_voter_mask``
    row — it is ignored for those rows (reference: ``Ballot`` with empty
    oldConf grants on the new conf alone).
    """
    new_q = quorum_match_index(match, voter_mask)
    old_q = quorum_match_index(match, old_voter_mask)
    in_joint = old_voter_mask.any(axis=-1)
    return jnp.where(in_joint, jnp.minimum(new_q, old_q), new_q)


def vote_quorum(granted: jnp.ndarray, voter_mask: jnp.ndarray) -> jnp.ndarray:
    """Per-group: does the granted-vote set reach a quorum of voters?

    granted: bool [..., P]; voter_mask: bool [..., P].
    Mirrors ``Ballot#isGranted`` for election and pre-vote tallies.
    """
    n_voters = voter_mask.sum(axis=-1).astype(jnp.int32)
    votes = (granted & voter_mask).sum(axis=-1).astype(jnp.int32)
    return (n_voters > 0) & (votes >= n_voters // 2 + 1)


def joint_vote_quorum(
    granted: jnp.ndarray, voter_mask: jnp.ndarray, old_voter_mask: jnp.ndarray
) -> jnp.ndarray:
    """Election quorum under joint consensus: both configs must grant."""
    new_ok = vote_quorum(granted, voter_mask)
    old_ok = vote_quorum(granted, old_voter_mask)
    in_joint = old_voter_mask.any(axis=-1)
    return jnp.where(in_joint, new_ok & old_ok, new_ok)


def witness_commit_clamp(
    quorum_idx: jnp.ndarray,
    match: jnp.ndarray,
    voter_mask: jnp.ndarray,
    old_voter_mask: jnp.ndarray,
    witness_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Clamp the commit point to the best DATA-replica match for groups
    with witness voters.

    Witnesses (util.quorum.witness_minority: a strict minority of
    metadata-only voters) count toward vote and ack quorums but hold no
    log payload, so an index acked only by witnesses must not commit —
    the host BallotBox clamps its quorum index to ``max(match[data])``
    (ballot_box.commit_point), and this is that clamp vectorized over
    the [G] axis.  Data peers are every voter (either config — the
    joint union mirrors the host's ``conf.data_peers + old_conf
    .data_peers``) not marked witness; groups without witnesses pass
    through untouched.  The max over an all-False data row is 0, like
    the host's ``max(..., default=0)`` — a witness-only quorum can
    never commit anything.
    """
    voters = voter_mask | old_voter_mask
    has_witness = (voters & witness_mask).any(axis=-1)
    data = voters & ~witness_mask
    data_best = jnp.where(data, match.astype(jnp.int32),
                          jnp.int32(0)).max(axis=-1)
    return jnp.where(has_witness, jnp.minimum(quorum_idx, data_best),
                     quorum_idx)


def quorum_ack_time(last_ack: jnp.ndarray, voter_mask: jnp.ndarray) -> jnp.ndarray:
    """q-th most recent peer ack timestamp — the leader-lease / step-down
    primitive (reference: ``NodeImpl#checkDeadNodes``): the leader's lease
    extends ``election_timeout`` past the time a quorum last responded.

    Identical math to :func:`quorum_match_index`; exposed under its own
    name because timestamps and log indexes are different host quantities.
    """
    return quorum_match_index(last_ack, voter_mask)


def joint_quorum_ack_time(
    last_ack: jnp.ndarray, voter_mask: jnp.ndarray, old_voter_mask: jnp.ndarray
) -> jnp.ndarray:
    """Lease/step-down ack point under joint consensus: the leader holds
    its lease only while a quorum of BOTH configurations is responsive
    (reference: ``NodeImpl#checkDeadNodes`` iterates conf and oldConf), so
    take the older (min) of the two configs' quorum ack times."""
    new_q = quorum_ack_time(last_ack, voter_mask)
    old_q = quorum_ack_time(last_ack, old_voter_mask)
    in_joint = old_voter_mask.any(axis=-1)
    return jnp.where(in_joint, jnp.minimum(new_q, old_q), new_q)
