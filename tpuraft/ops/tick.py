"""The fused multi-group tick kernel.

One jitted function advances ALL G raft groups' quorum math at once
(SURVEY.md §8 "Device plane"): commit-index advancement, election vote
tallies, election-timeout firing, leader-lease/step-down checks, and
heartbeat scheduling.  The host runtime (tpuraft.core.engine) merges
protocol events (RPC responses, fsync acks) into the state arrays between
ticks and applies the emitted event masks (elected / step_down /
start_prevote) through the slow-path protocol code.

Division of labor:
  - device mutates only *derived, monotone* state (commit_rel, hb_deadline);
  - role/term/vote transitions are host-applied from output masks, so the
    host remains the single writer of protocol state (the functional
    analog of NodeImpl's writeLock discipline).

All times are int32 milliseconds relative to engine start; all log indexes
are int32 relative to a per-group host-managed base (see tpuraft.ops).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from tpuraft.ops.ballot import NEG_INF_I32, witness_commit_clamp
from tpuraft.ops.quorum_pallas import fused_quorum

# Role encoding (device plane). Learners are not a role: they sit in peer
# slots with voter_mask=False.
ROLE_FOLLOWER = 0
ROLE_CANDIDATE = 1
ROLE_LEADER = 2
ROLE_INACTIVE = 3  # unallocated group slot


@jax.tree_util.register_dataclass
@dataclass
class GroupState:
    """Structure-of-arrays consensus state for G groups x P peer slots.

    This is this *node's* local view of each group it participates in —
    the vectorized replacement for the reference's per-group object graph
    (NodeImpl + BallotBox + ReplicatorGroup matchIndex bookkeeping).
    """

    role: jnp.ndarray          # int32 [G]
    commit_rel: jnp.ndarray    # int32 [G]  committed index - base
    pending_rel: jnp.ndarray   # int32 [G]  first index of current leadership
    match_rel: jnp.ndarray     # int32 [G,P] acked matchIndex - base (self slot = lastLog)
    granted: jnp.ndarray       # bool  [G,P] votes granted this election round
    voter_mask: jnp.ndarray    # bool  [G,P] voters in current conf
    old_voter_mask: jnp.ndarray  # bool [G,P] voters in old conf (joint) else False
    elect_deadline: jnp.ndarray  # int32 [G] ms: follower election-timeout deadline
    hb_deadline: jnp.ndarray   # int32 [G] ms: leader next-heartbeat time
    last_ack: jnp.ndarray      # int32 [G,P] ms: last response time per peer
    snap_deadline: jnp.ndarray  # int32 [G] ms: next snapshot due (engine-
    # scheduled snapshotTimer: one [G] row + mask replaces G RepeatedTimers)
    quiescent: jnp.ndarray     # bool [G] hibernating group: beats and
    # election timeouts suppressed on device; liveness is delegated to the
    # store-level lease (HeartbeatHub), which wakes the group on expiry.
    # step_down stays LIVE for quiescent leaders — the host refreshes
    # their last_ack rows from store-lease acks, so a dead store still
    # deposes its quiescent leaders through ordinary ack staleness.
    witness_mask: jnp.ndarray  # bool [G,P] witness voters (either config):
    # metadata-only replicas that vote and ack but hold no log payload —
    # the commit point is clamped to the best data-replica match
    # (ballot.witness_commit_clamp, the vectorized BallotBox clamp)
    stepdown_deadline: jnp.ndarray  # int32 [G] ms: leader's next periodic
    # stepdown/priority check (the reference's stepDownTimer cadence,
    # eto/2) — fires Node._check_dead_nodes, which re-verifies the quorum
    # AND accrues priority_transfer_rounds toward transfer-back
    fence_start: jnp.ndarray   # int32 [G] ms: earliest pending read-fence
    # start time, NEG_INF when no fence is pending — the device resolves
    # a ReadConfirmBatcher round when the fused q_ack reduction reaches
    # it (fence_ok), replacing the per-round host-side ack-set tally

    @staticmethod
    def zeros(g: int, p: int) -> "GroupState":
        return GroupState(
            role=jnp.full((g,), ROLE_INACTIVE, jnp.int32),
            commit_rel=jnp.zeros((g,), jnp.int32),
            pending_rel=jnp.ones((g,), jnp.int32),
            match_rel=jnp.zeros((g, p), jnp.int32),
            granted=jnp.zeros((g, p), bool),
            voter_mask=jnp.zeros((g, p), bool),
            old_voter_mask=jnp.zeros((g, p), bool),
            elect_deadline=jnp.zeros((g,), jnp.int32),
            hb_deadline=jnp.zeros((g,), jnp.int32),
            last_ack=jnp.zeros((g, p), jnp.int32),
            snap_deadline=jnp.zeros((g,), jnp.int32),
            quiescent=jnp.zeros((g,), bool),
            witness_mask=jnp.zeros((g, p), bool),
            stepdown_deadline=jnp.zeros((g,), jnp.int32),
            fence_start=jnp.full((g,), NEG_INF_I32, jnp.int32),
        )


@jax.tree_util.register_dataclass
@dataclass
class TickParams:
    """Protocol parameters: int32 scalars (engine-wide) or [G] rows
    (per-group — the reference's per-node NodeOptions timeouts; a PD
    group and region groups in one engine each honor their own).  Either
    shape broadcasts through the tick; prefetched once, not retraced."""

    election_timeout_ms: jnp.ndarray  # int32 scalar or [G]
    heartbeat_ms: jnp.ndarray         # int32 scalar or [G]
    lease_ms: jnp.ndarray             # int32 scalar or [G]
    snapshot_ms: jnp.ndarray          # int32 scalar or [G]; 0 = disabled

    @staticmethod
    def make(election_timeout_ms, heartbeat_ms, lease_ms,
             snapshot_ms=0) -> "TickParams":
        return TickParams(
            jnp.asarray(election_timeout_ms, jnp.int32),
            jnp.asarray(heartbeat_ms, jnp.int32),
            jnp.asarray(lease_ms, jnp.int32),
            jnp.asarray(snapshot_ms, jnp.int32),
        )


@jax.tree_util.register_dataclass
@dataclass
class TickOutputs:
    """Per-tick event masks + advanced indexes the host applies."""

    commit_rel: jnp.ndarray     # int32 [G] new commit (== old where unchanged)
    commit_advanced: jnp.ndarray  # bool [G]
    elected: jnp.ndarray        # bool [G] candidate reached vote quorum
    election_due: jnp.ndarray   # bool [G] follower/candidate election timer fired
    step_down: jnp.ndarray      # bool [G] leader lost quorum within lease window
    hb_due: jnp.ndarray         # bool [G] leader heartbeat due this tick
    lease_valid: jnp.ndarray    # bool [G] leader lease currently valid (for reads)
    snap_due: jnp.ndarray       # bool [G] snapshot interval elapsed (any role)
    q_ack: jnp.ndarray          # int32 [G] q-th newest voter ack time (the
    # lease_valid lane's raw input, NEG_INF when no data) — the host keeps
    # the last tick's row as a LOWER bound on the current quorum-ack time,
    # so per-read lease checks (ReadOnlyOption.LEASE_BASED) answer off the
    # fused reduction instead of re-sorting a [P] row per read
    stepdown_due: jnp.ndarray   # bool [G] leader's periodic stepdown/
    # priority check fired (Node._check_dead_nodes slow path)
    fence_ok: jnp.ndarray       # bool [G] pending read fence satisfied:
    # the quorum-ack point reached fence_start (host resolves + re-arms)


def raft_tick(state: GroupState, now_ms: jnp.ndarray, params: TickParams,
              quorum_impl: str | None = None
              ) -> tuple[GroupState, TickOutputs]:
    """Advance all groups one tick. Pure; jit/shard_map over the G axis.

    quorum_impl selects the [G,P]-reduction backend (see
    tpuraft.ops.quorum_pallas.fused_quorum); it must be static under jit.
    """
    is_leader = state.role == ROLE_LEADER
    is_follower = state.role == ROLE_FOLLOWER
    is_candidate = state.role == ROLE_CANDIDATE

    # The three [G,P] -> [G] quorum reductions in one (fusable) pass.
    quorum_idx, vote_ok, q_ack = fused_quorum(
        state.match_rel, state.granted, state.last_ack,
        state.voter_mask, state.old_voter_mask, impl=quorum_impl)

    # --- commit advancement (BallotBox#commitAt, vectorized) ---------------
    # Entries before pending_rel belong to prior leaderships: never counted
    # (this IS the Raft §5.4.2 current-term commit gate — pending_rel is set
    # to lastLogIndex+1 at becomeLeader, mirroring BallotBox#resetPendingIndex).
    # Witness confs: votes and acks count every voter (quorums above are
    # correct as-is), but the COMMIT point is clamped to the best
    # data-replica match — an index held only by metadata witnesses is
    # not durable on any log.  Applied after fused_quorum so the fused
    # reduction (including its pallas backend) stays witness-agnostic.
    quorum_idx = witness_commit_clamp(
        quorum_idx, state.match_rel, state.voter_mask,
        state.old_voter_mask, state.witness_mask)
    can_commit = is_leader & (quorum_idx >= state.pending_rel)
    new_commit = jnp.where(
        can_commit, jnp.maximum(state.commit_rel, quorum_idx), state.commit_rel
    )
    commit_advanced = new_commit > state.commit_rel

    # --- election tally (NodeImpl#handleRequestVoteResponse, vectorized) ---
    elected = is_candidate & vote_ok

    # --- election timeout (RepeatedTimer electionTimer, vectorized) --------
    # Quiescent followers suppress their election timeout: liveness for a
    # hibernating group rides the store-level lease, and the lease-expiry
    # wake path re-arms the deadline (with fresh jitter) before clearing
    # the quiescent bit — so the mask can never fire on stale deadlines.
    election_due = (is_follower | is_candidate) & ~state.quiescent & (
        now_ms >= state.elect_deadline)

    # --- leader lease / step-down (NodeImpl#checkDeadNodes) ----------------
    # Count the leader itself as acked "now" via its self slot: the host
    # keeps last_ack[g, self] == now. Quorum ack time = q-th newest response.
    # The NEG gate below means "no data", not "dead quorum"; the host
    # upholds the invariant that a LEADER's voter columns are never NEG
    # (grace stamps at on_leader and for set_conf-added peers), so a
    # config that stops responding always reaches step_down via staleness.
    have_quorum_ack = q_ack > NEG_INF_I32
    lease_valid = is_leader & have_quorum_ack & (now_ms - q_ack < params.lease_ms)
    step_down = is_leader & have_quorum_ack & (
        now_ms - q_ack >= params.election_timeout_ms
    )

    # --- periodic stepdown/priority lane (RepeatedTimer stepDownTimer) -----
    # Timer-mode nodes run _check_dead_nodes every eto/2 regardless of
    # quorum health, and that cadence is what accrues
    # priority_transfer_rounds (a decay-elected leader hands leadership
    # back when a higher-priority peer recovers).  The engine previously
    # only fired the handler on DEAD quorums, so engine leaders never
    # transferred back — this lane restores the periodic cadence on
    # device.  Quiescent leaders skip it: their quorum rides the store
    # lease, and waking for a priority scan would defeat hibernation.
    stepdown_due = is_leader & ~state.quiescent & (
        now_ms >= state.stepdown_deadline)
    new_stepdown_deadline = jnp.where(
        stepdown_due,
        now_ms + jnp.maximum(params.election_timeout_ms // 2, 1),
        state.stepdown_deadline)

    # --- device read-fence tally (ReadConfirmBatcher rounds) ---------------
    # A pending SAFE ReadIndex round armed fence_start = its start time;
    # the round is confirmed once a voter quorum acked AT OR AFTER it —
    # exactly the fused q_ack order statistic already computed above, so
    # the tally rides the existing reduction instead of a host-side
    # per-round ack-set.  The host clears/re-arms fence_start (it owns
    # the pending-fence queue); the row passes through unchanged.
    fence_ok = is_leader & (state.fence_start > NEG_INF_I32) & \
        have_quorum_ack & (q_ack >= state.fence_start)

    # --- heartbeat scheduling ---------------------------------------------
    # Quiescent leaders beat nothing: idle beat traffic collapses from
    # O(G x P) rows to the store-level lease's O(stores^2) RPCs.  The
    # step_down mask above intentionally stays ungated — store-lease acks
    # refresh quiescent leaders' last_ack rows host-side, so a silent
    # store still deposes its hibernating leaders within one timeout.
    hb_due = is_leader & ~state.quiescent & (now_ms >= state.hb_deadline)
    new_hb_deadline = jnp.where(hb_due, now_ms + params.heartbeat_ms, state.hb_deadline)

    # --- snapshot cadence (RepeatedTimer snapshotTimer, vectorized) --------
    # Any ACTIVE role snapshots (followers compact their logs too, like
    # the reference's per-node snapshotTimer); 0 disables.  The deadline
    # row advances on device; the host re-mirrors + jitters on fire.
    active = state.role != ROLE_INACTIVE
    snap_due = active & (params.snapshot_ms > 0) & (
        now_ms >= state.snap_deadline)
    new_snap_deadline = jnp.where(
        snap_due, now_ms + params.snapshot_ms, state.snap_deadline)

    new_state = GroupState(
        role=state.role,
        commit_rel=new_commit,
        pending_rel=state.pending_rel,
        match_rel=state.match_rel,
        granted=state.granted,
        voter_mask=state.voter_mask,
        old_voter_mask=state.old_voter_mask,
        elect_deadline=state.elect_deadline,
        hb_deadline=new_hb_deadline,
        last_ack=state.last_ack,
        snap_deadline=new_snap_deadline,
        quiescent=state.quiescent,
        witness_mask=state.witness_mask,
        stepdown_deadline=new_stepdown_deadline,
        fence_start=state.fence_start,
    )
    outputs = TickOutputs(
        commit_rel=new_commit,
        commit_advanced=commit_advanced,
        elected=elected,
        election_due=election_due,
        step_down=step_down,
        hb_due=hb_due,
        lease_valid=lease_valid,
        snap_due=snap_due,
        q_ack=q_ack,
        stepdown_due=stepdown_due,
        fence_ok=fence_ok,
    )
    return new_state, outputs


raft_tick_jit = jax.jit(raft_tick, donate_argnums=(0,),
                        static_argnames=("quorum_impl",))


def raft_tick_outputs(state: GroupState, now_ms: jnp.ndarray,
                      params: TickParams) -> TickOutputs:
    """Outputs-only tick — what the engine consumes (its numpy mirrors
    are the state of record between ticks, so the new GroupState is
    never fetched)."""
    return raft_tick(state, now_ms, params)[1]


# ONE process-wide jitted instance: every MultiRaftEngine in the process
# shares this trace cache, so the N-th engine's first tick does not
# re-trace/re-compile (a ~0.5s event-loop stall per engine that round-1
# style multi-engine tests turned into election storms).
raft_tick_outputs_jit = jax.jit(raft_tick_outputs)


def witness_lanes_available() -> bool:
    """Does the loaded device plane carry the witness/priority/fence
    parity lanes?  StoreEngine consults this before accepting a witness
    conf on an engine-backed store: against an older tick kernel (e.g. a
    stale deployment mixing wheel versions) the [G,P] ballot plane would
    count witness acks as durable and commit unreplicated entries, so
    the boot refusal stays — with an error that names the missing lane
    rather than a blanket "engines can't do witnesses"."""
    return ("witness_mask" in GroupState.__dataclass_fields__
            and "fence_ok" in TickOutputs.__dataclass_fields__)
