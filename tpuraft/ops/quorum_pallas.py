"""Pallas TPU kernel for the fused quorum hot path.

One kernel pass computes, for every raft group, the three [G,P]→[G]
reductions of the tick (SURVEY.md §4.2 — ``BallotBox#commitAt`` +
vote tally + ``NodeImpl#checkDeadNodes``):

  quorum_idx  — q-th largest voter matchIndex (joint-consensus aware)
  elected     — vote quorum reached (joint-consensus aware)
  q_ack       — q-th newest voter ack timestamp (joint-consensus aware;
                lease / step-down)

Design notes:
  - Arrays enter transposed as [P, G] so the large G axis lies on the
    128-lane dimension (P <= 16 would waste 112/128 lanes the other way).
  - The q-th order statistic uses rank counting, not sorting: for slot j,
    cnt_j = #{k : v_k >= v_j}; the q-th largest = max{v_j : cnt_j >= q}.
    That is P broadcast-compare-accumulates over [P, TILE_G] tiles — pure
    VPU work, no gather/sort, and P is a static Python loop (fully
    unrolled at trace time, as the guide prescribes for tiny axes).
  - Masks arrive as int32 (bool tiles would demand 32 sublanes; P < 32).
  - One G-tile per grid step; all five inputs for a tile sit in VMEM
    (5 * P * TILE_G * 4B = 128KB at P=16, TILE_G=512 — far under 16MB).

The XLA fallback (tpuraft.ops.ballot) stays the source of truth for
semantics; tests drive both paths (kernel under ``interpret=True`` on
CPU) over randomized states and assert bit-equality.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpuraft.ops.ballot import (
    joint_quorum_ack_time,
    joint_quorum_match_index,
    joint_vote_quorum,
)

TILE_G = 512
_NEG_INF = -(2 ** 30)  # plain int: a jnp constant would be captured by the kernel


def _qth_largest(v: jnp.ndarray, mask: jnp.ndarray, p: int) -> jnp.ndarray:
    """[P, T] masked values -> [1, T] q-th largest among mask, else NEG_INF."""
    vm = jnp.where(mask, v, jnp.int32(_NEG_INF))
    n_voters = mask.astype(jnp.int32).sum(axis=0, keepdims=True)   # [1, T]
    q = n_voters // 2 + 1
    cnt = jnp.zeros(v.shape, jnp.int32)                            # [P, T]
    for k in range(p):  # static unroll: P broadcast-compares on the VPU
        cnt = cnt + (vm[k:k + 1, :] >= vm).astype(jnp.int32)
    ok = mask & (cnt >= q)
    picked = jnp.where(ok, vm, jnp.int32(_NEG_INF)).max(axis=0, keepdims=True)
    return jnp.where(n_voters > 0, picked, jnp.int32(_NEG_INF))


def _vote_quorum(granted: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    n_voters = mask.astype(jnp.int32).sum(axis=0, keepdims=True)
    votes = (granted & mask).astype(jnp.int32).sum(axis=0, keepdims=True)
    return (n_voters > 0) & (votes >= n_voters // 2 + 1)


def _fused_quorum_kernel(match_ref, granted_ref, ack_ref, vm_ref, ovm_ref,
                         qidx_ref, elected_ref, qack_ref):
    p = match_ref.shape[0]
    vm = vm_ref[:] != 0
    ovm = ovm_ref[:] != 0
    granted = granted_ref[:] != 0
    in_joint = ovm.astype(jnp.int32).max(axis=0, keepdims=True) > 0  # [1, T]

    qi_new = _qth_largest(match_ref[:], vm, p)
    qi_old = _qth_largest(match_ref[:], ovm, p)
    qidx_ref[:] = jnp.where(in_joint, jnp.minimum(qi_new, qi_old), qi_new)

    el_new = _vote_quorum(granted, vm)
    el_old = _vote_quorum(granted, ovm)
    elected_ref[:] = jnp.where(in_joint, el_new & el_old,
                               el_new).astype(jnp.int32)

    qa_new = _qth_largest(ack_ref[:], vm, p)
    qa_old = _qth_largest(ack_ref[:], ovm, p)
    qack_ref[:] = jnp.where(in_joint, jnp.minimum(qa_new, qa_old), qa_new)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_quorum_pallas(match, granted, last_ack, voter_mask, old_voter_mask,
                         interpret: bool = False):
    g, p = match.shape
    # lane tiles must be 128-multiples: round G up to 128, cap the tile at
    # TILE_G, then round G up again to a whole number of tiles
    tile = min(TILE_G, -(-g // 128) * 128)
    pad = (-g) % tile
    # pad G to a tile multiple with inactive groups (all-False masks)
    if pad:
        zi = lambda a: jnp.pad(a, ((0, pad), (0, 0)))  # noqa: E731
        match, last_ack = zi(match), zi(last_ack)
        granted = jnp.pad(granted, ((0, pad), (0, 0)))
        voter_mask = jnp.pad(voter_mask, ((0, pad), (0, 0)))
        old_voter_mask = jnp.pad(old_voter_mask, ((0, pad), (0, 0)))
    gp = g + pad
    t = lambda a: a.T.astype(jnp.int32)  # noqa: E731 — [G,P] -> [P,G] lanes
    spec_in = pl.BlockSpec((p, tile), lambda i: (0, i))
    spec_out = pl.BlockSpec((1, tile), lambda i: (0, i))
    qidx, elected, qack = pl.pallas_call(
        _fused_quorum_kernel,
        grid=(gp // tile,),
        in_specs=[spec_in] * 5,
        out_specs=[spec_out] * 3,
        out_shape=[jax.ShapeDtypeStruct((1, gp), jnp.int32)] * 3,
        interpret=interpret,
    )(t(match), t(granted), t(last_ack), t(voter_mask), t(old_voter_mask))
    return qidx[0, :g], elected[0, :g] != 0, qack[0, :g]


def _fused_quorum_xla(match, granted, last_ack, voter_mask, old_voter_mask):
    qidx = joint_quorum_match_index(match, voter_mask, old_voter_mask)
    elected = joint_vote_quorum(granted, voter_mask, old_voter_mask)
    qack = joint_quorum_ack_time(last_ack, voter_mask, old_voter_mask)
    return qidx, elected, qack


def select_impl(g: int = 256, p: int = 8) -> tuple[str, str]:
    """Probe whether the Pallas kernel compiles+runs on the CURRENT
    default device; returns ("pallas"|"xla", reason).  Auto-selection
    seam for engine start / benchmarks (VERDICT r1 #4): on
    direct-attached TPUs the kernel lights up; over remote-compile
    tunnels (Mosaic HTTP 500) or CPU backends it falls back to XLA with
    the reason recorded instead of crashing the runtime."""
    import numpy as np

    try:
        zeros_i = jnp.zeros((g, p), jnp.int32)
        zeros_b = jnp.zeros((g, p), bool)
        vm = np.zeros((g, p), bool)
        vm[:, :3] = True
        out = _fused_quorum_pallas(zeros_i, zeros_b, zeros_i,
                                   jnp.asarray(vm), zeros_b)
        jax.block_until_ready(out)
        return "pallas", "kernel compiled and ran on the default device"
    except Exception as e:  # noqa: BLE001 — any compile/runtime failure
        import re

        msg = re.sub(r"\x1b\[[0-9;]*m", "", str(e))       # ANSI colors
        msg = " ".join(msg.split())                        # newlines/runs
        return "xla", f"pallas unavailable: {type(e).__name__}: {msg[:160]}"


def fused_quorum(match, granted, last_ack, voter_mask, old_voter_mask,
                 impl: str | None = None):
    """(quorum_idx[G], elected[G], q_ack[G]) from the [G,P] state planes.

    impl: "pallas" (TPU kernel), "pallas_interpret" (CPU-debuggable
    kernel), "xla" (pure jnp), or None = $TPURAFT_QUORUM_IMPL, default
    "xla".  The default stays XLA even on TPU backends for now: XLA fuses
    this chain well, and tunneled-TPU environments (axon) cannot compile
    Mosaic kernels reliably; flip the env var on direct-attached TPU
    hardware to A/B the kernel.
    """
    if impl is None:
        impl = os.environ.get("TPURAFT_QUORUM_IMPL", "xla")
    if impl == "pallas":
        return _fused_quorum_pallas(match, granted, last_ack,
                                    voter_mask, old_voter_mask)
    if impl == "pallas_interpret":
        return _fused_quorum_pallas(match, granted, last_ack,
                                    voter_mask, old_voter_mask,
                                    interpret=True)
    if impl == "xla":
        return _fused_quorum_xla(match, granted, last_ack,
                                 voter_mask, old_voter_mask)
    raise ValueError(f"unknown quorum impl: {impl}")
