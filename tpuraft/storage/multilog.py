"""Shared multi-group log engine bindings (native/multilog.cc).

Reference parity: RocksDB as ONE log engine per process — all raft
groups share a write stream and a flush round covers every group with a
single fsync (``core:storage/impl/RocksDBLogStorage`` + RocksDB
WriteBatch; SURVEY.md §3.1 log-storage row, §8.3 "group-sharded column
spaces; batched group-fsync").  Round-1 gap (VERDICT #3): every group
opened its own segment directory, so a process hosting 1K regions held
thousands of fds and issued uncoalesced fsyncs.

Wiring:
  log_uri = "multilog://<dir>#<group_id>"
One :class:`MultiLogEngine` per directory per process (registry below);
each node's :class:`MultiLogStorage` is a per-group view.  Durability:
``append_entries`` stages bytes; the engine's :class:`_GroupCommit`
coalesces every concurrently-flushing group into ONE ``tlm_sync``
(observable via ``sync_count``/``append_count``).  The LogManager uses
the async ``append_entries_async`` hook when present, so flush waiters
are futures, not blocked executor threads.
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import struct
import threading
import time
from typing import Optional

from tpuraft.entity import LogEntry
from tpuraft.storage.log_storage import CorruptLogError, LogStorage

_FRAME = struct.Struct("<I")
_LIB_NAME = "libtpuraft_multilog.so"


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")


def ensure_built(timeout: float = 120.0) -> str:
    from tpuraft.util.native_build import ensure_built as _eb
    return _eb(_native_dir(), os.path.join(_native_dir(), _LIB_NAME),
               target=_LIB_NAME, timeout=timeout)


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(ensure_built())
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tlm_open.restype = ctypes.c_void_p
            lib.tlm_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char_p, ctypes.c_int]
            lib.tlm_close.argtypes = [ctypes.c_void_p]
            lib.tlm_register_group.restype = ctypes.c_uint32
            lib.tlm_register_group.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_int]
            for name in ("tlm_first", "tlm_last"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
            lib.tlm_append.restype = ctypes.c_int64
            lib.tlm_append.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                       ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.c_char_p, ctypes.c_int]
            lib.tlm_sync.restype = ctypes.c_int
            lib.tlm_sync.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
            for name in ("tlm_sync_count", "tlm_append_count",
                         "tlm_file_count", "tlm_gc"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p]
            lib.tlm_get.restype = ctypes.c_int64
            lib.tlm_get.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.c_int64, ctypes.POINTER(u8p)]
            lib.tlm_free.argtypes = [u8p]
            for name in ("tlm_truncate_prefix", "tlm_truncate_suffix",
                         "tlm_reset"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
                fn.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.c_int64]
            lib.tlm_conf_count.restype = ctypes.c_int64
            lib.tlm_conf_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
            lib.tlm_conf_indexes.restype = ctypes.c_int64
            lib.tlm_conf_indexes.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
            _lib = lib
        return _lib


def _deliver(f: asyncio.Future, exc: Optional[BaseException]) -> None:
    """Resolve one group-commit waiter; must run on f's own loop."""
    if f.done():
        return
    if exc is not None:
        f.set_exception(exc)
    else:
        f.set_result(None)


class _GroupCommit:
    """Coalesces concurrent flush() calls into one tlm_sync round
    (RocksDB group commit): callers that arrive while a round's fsync is
    in flight wait for the NEXT round, which covers their staged bytes.

    The engine is shared process-wide by directory, so flushers may live
    on DIFFERENT event loops (multi-store processes): the waiter list is
    lock-guarded and each future resolves on its OWN loop — setting a
    future from a foreign loop's thread is not thread-safe."""

    # An inline fsync blocks the event loop, so the fast path self-bans
    # the moment a sync exceeds this (slow/contended disk): stalling the
    # loop stalls heartbeats for EVERY group in the process.
    INLINE_MAX_S = 0.001
    # Gap below which another flush is considered "hot on our heels":
    # take the coalescing round so N concurrent flushers cost one fsync.
    INLINE_IDLE_GAP_S = 0.002

    def __init__(self, engine: "MultiLogEngine"):
        self._engine = engine
        self._lock = threading.Lock()
        self._waiters: list[asyncio.Future] = []   # guarded-by: _lock
        self._task: Optional[asyncio.Task] = None  # guarded-by: _lock
        self._last_sync = 0.0                      # guarded-by: _lock
        # smoothed inline-sync cost (seconds)
        self._cost_ewma = 0.0                      # guarded-by: _lock
        # gray-failure signal sink: a DiskLatencyProbe (util/health.py,
        # itself lock-guarded) fed every measured fsync duration — set
        # by the hosting StoreEngine; None = no health scoring
        self.health_probe = None

    async def flush(self) -> None:
        # LOW-LOAD fast path (VERDICT r2 #3): the executor round costs
        # ~2ms end-to-end on a busy single-core loop (the completion
        # callback queues behind tick + replicator work) while the fsync
        # itself is ~0.1ms on this disk class.  When no round is running
        # and no flush landed within the idle gap, fsync INLINE — the
        # commit-ack path shortens by the round-trip on both the leader
        # and the follower.  Sustained load (back-to-back flushes) keeps
        # the coalescing round: N concurrent flushers -> one fsync.
        with self._lock:
            idle = (self._task is None or self._task.done()) and \
                (time.monotonic() - self._last_sync
                 > self.INLINE_IDLE_GAP_S)
            # NOTE: while banned (ewma >= INLINE_MAX_S) there is no
            # inline re-probe — a probe blocks the loop for the full,
            # unbounded fsync (seconds under writeback stalls), for
            # every group in the process.  The executor round measures
            # each sync instead (in _run) and the same EWMA recovers
            # there, so the fast path re-enables only after the DISK
            # proves fast again, off-loop.
            if idle and self._cost_ewma < self.INLINE_MAX_S \
                    and not self._waiters:
                self._last_sync = time.monotonic()  # claim the window
                inline = True
            else:
                inline = False
                fut = asyncio.get_running_loop().create_future()
                self._waiters.append(fut)
                # done() covers a round task that died without its
                # locked handoff (its loop closed with the task
                # pending): the next flusher revives the group commit
                if self._task is None or self._task.done():
                    self._task = asyncio.ensure_future(self._run())
        if inline:
            t0 = time.perf_counter()
            try:
                self._engine.sync()
            finally:
                dur = time.perf_counter() - t0
                with self._lock:
                    self._last_sync = time.monotonic()
                    # smoothed: one writeback spike doesn't ban the fast
                    # path, a genuinely slow disk does (and keeps it
                    # banned while the ewma stays above the ceiling)
                    self._cost_ewma = 0.7 * self._cost_ewma + 0.3 * dur
                probe = self.health_probe
                if probe is not None:
                    probe.note(dur)
            return
        await fut

    def _timed_sync(self) -> float:
        """engine.sync() + pure in-thread duration (seconds)."""
        t0 = time.perf_counter()
        self._engine.sync()
        return time.perf_counter() - t0

    def _revive(self) -> None:
        """Restart the round on THIS loop — scheduled via
        call_soon_threadsafe when a foreign host loop died mid-round."""
        with self._lock:
            if self._waiters and (self._task is None or self._task.done()):
                self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            with self._lock:
                if not self._waiters:
                    # hand off INSIDE the lock: a flusher on another loop
                    # that observed a still-pending task must not strand
                    # its waiter on a round that already decided to exit
                    self._task = None
                    return
                batch, self._waiters = self._waiters, []
            exc: Optional[BaseException] = None
            try:
                # time the fsync IN the executor thread: timing around
                # the await would fold in the loop round-trip (~2ms) and
                # permanently ban the inline path on any busy process
                dur = await loop.run_in_executor(None, self._timed_sync)
                with self._lock:
                    self._last_sync = time.monotonic()
                    # keep the inline-ban EWMA fed from the executor
                    # path too: this is how a banned fast path recovers
                    # (re-probing inline would block the loop)
                    self._cost_ewma = 0.7 * self._cost_ewma + 0.3 * dur
                probe = self.health_probe
                if probe is not None:
                    probe.note(dur)
            except asyncio.CancelledError:
                # this round's HOST loop is tearing down (asyncio.run
                # cancels pending tasks at exit) — that is not an fsync
                # failure, and waiters on OTHER loops must not see it:
                # requeue the batch, hand the round to every surviving
                # waiter loop (idempotent under the lock), and let the
                # cancellation proceed on this loop
                with self._lock:
                    self._waiters = batch + self._waiters
                    self._task = None
                    for fl in {f.get_loop() for f in self._waiters}:
                        if fl is loop:
                            continue
                        try:
                            fl.call_soon_threadsafe(self._revive)
                        except RuntimeError:
                            pass  # that loop is gone too
                raise
            except Exception as e:  # noqa: BLE001 — fail THIS round only
                exc = e
            for f in batch:
                if f.get_loop() is loop:
                    _deliver(f, exc)
                else:
                    try:
                        f.get_loop().call_soon_threadsafe(_deliver, f, exc)
                    except RuntimeError:
                        pass  # waiter's loop already closed


class MultiLogEngine:
    """One shared journal engine (ctypes handle) + its group-commit."""

    def __init__(self, dir_path: str, segment_max_bytes: int = 0):
        self._lib = _load()
        parent = os.path.dirname(dir_path.rstrip("/"))
        if parent:
            os.makedirs(parent, exist_ok=True)
        err = ctypes.create_string_buffer(256)
        self._h = self._lib.tlm_open(dir_path.encode(), segment_max_bytes,
                                     err, 256)
        if not self._h:
            raise IOError(f"multilog open failed: {err.value.decode()}")
        self.dir = dir_path
        self.group_commit = _GroupCommit(self)
        # capacity-fault hook (tests/soak): a callable taking the byte
        # count about to be staged, raising OSError(ENOSPC) to refuse it
        # — the C++ fd writes are out of Python interposition's reach,
        # so NativeJournalTracker.attach_quota enforces budgets here
        self.fault_gate = None
        self._refs = 0
        # serializes sync vs close: tlm_close deletes the native Store,
        # so closing while an fsync round is mid-flight in any thread
        # (executor, or a foreign loop's cancelled round whose job keeps
        # running) would be a use-after-free.  close() blocks the few ms
        # an in-flight fsync needs; later syncs fail cleanly.
        self._sync_lock = threading.Lock()

    def close(self) -> None:
        with self._sync_lock:
            if self._h:
                self._lib.tlm_close(self._h)
                self._h = None

    def register_group(self, name: str) -> int:
        err = ctypes.create_string_buffer(256)
        gid = self._lib.tlm_register_group(self._h, name.encode(), err, 256)
        if gid == 0:
            raise IOError(f"multilog register failed: {err.value.decode()}")
        return gid

    def sync(self) -> None:
        with self._sync_lock:
            h = self._h
            if not h:
                raise IOError("multilog engine closed")
            err = ctypes.create_string_buffer(256)
            if self._lib.tlm_sync(h, err, 256) != 0:
                raise IOError(f"multilog sync failed: {err.value.decode()}")

    @property
    def sync_count(self) -> int:
        return self._lib.tlm_sync_count(self._h)

    @property
    def append_count(self) -> int:
        return self._lib.tlm_append_count(self._h)

    @property
    def file_count(self) -> int:
        return self._lib.tlm_file_count(self._h)

    def gc(self) -> int:
        return self._lib.tlm_gc(self._h)


# -- process-level engine registry (one engine per directory) ----------------

_engines_lock = threading.Lock()
_engines: dict[str, MultiLogEngine] = {}  # guarded-by: _engines_lock


def peek_engine(dir_path: str) -> Optional[MultiLogEngine]:
    """The live engine for a directory WITHOUT taking a reference —
    observability wiring (the StoreEngine attaching its health probe),
    never ownership."""
    key = os.path.realpath(dir_path)
    with _engines_lock:
        return _engines.get(key)


def get_engine(dir_path: str, segment_max_bytes: int = 0) -> MultiLogEngine:
    key = os.path.realpath(dir_path)
    with _engines_lock:
        eng = _engines.get(key)
        if eng is None or eng._h is None:
            eng = MultiLogEngine(dir_path, segment_max_bytes)
            _engines[key] = eng
        eng._refs += 1
        return eng


def _release_engine(eng: MultiLogEngine) -> None:
    key = os.path.realpath(eng.dir)
    with _engines_lock:
        eng._refs -= 1
        if eng._refs > 0:
            return
        _engines.pop(key, None)
    # close() serializes against any in-flight fsync via the engine's
    # sync lock (blocks the few ms it needs), so closing here is safe
    # even while a round's executor job is still running; that round's
    # waiters — all belonging to already-shutdown stores — get a clean
    # "engine closed" failure if they sync after this point
    eng.close()


class MultiLogStorage(LogStorage):

    CHEAP_CONF_INDEXES = True  # C-side sidecar lookup, no disk I/O
    """Per-group view over the shared engine; selected by
    ``multilog://<dir>#<group_id>``."""

    def __init__(self, dir_path: str, group: str):
        self._dir = dir_path
        self._group = group
        self._eng: Optional[MultiLogEngine] = None
        self._gid = 0
        self._lib = _load()

    @property
    def engine(self) -> MultiLogEngine:
        assert self._eng is not None, "init() first"
        return self._eng

    def init(self) -> None:
        self._eng = get_engine(self._dir)
        self._gid = self._eng.register_group(self._group)

    def shutdown(self) -> None:
        if self._eng is not None:
            _release_engine(self._eng)
            self._eng = None

    def first_log_index(self) -> int:
        return self._lib.tlm_first(self._eng._h, self._gid)

    def last_log_index(self) -> int:
        return self._lib.tlm_last(self._eng._h, self._gid)

    def get_entry(self, index: int) -> Optional[LogEntry]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.tlm_get(self._eng._h, self._gid, index,
                              ctypes.byref(out))
        if n == -2:
            # the index says the record is live but its CRC fails: bit
            # rot of acked data — silently returning None here would
            # read as a hole and could ship garbage to a follower
            raise CorruptLogError(
                f"multilog record for group {self._group} index {index} "
                f"fails CRC — acked entry corrupted")
        if n < 0:
            return None
        try:
            blob = ctypes.string_at(out, n)
        finally:
            self._lib.tlm_free(out)
        return LogEntry.decode(blob)

    def _stage(self, entries: list[LogEntry]) -> int:
        parts = []
        for e in entries:
            blob = e.encode()
            parts.append(_FRAME.pack(len(blob)))
            parts.append(blob)
        frames = b"".join(parts)
        gate = self._eng.fault_gate
        if gate is not None:
            gate(len(frames))
        err = ctypes.create_string_buffer(256)
        n = self._lib.tlm_append(self._eng._h, self._gid, frames,
                                 len(frames), err, 256)
        if n < 0:
            raise ValueError(f"multilog append failed: {err.value.decode()}")
        return n

    def append_entries(self, entries: list[LogEntry], sync: bool = True) -> int:
        """Synchronous path (executor callers): per-call fsync, no
        cross-group coalescing — prefer append_entries_async."""
        if not entries:
            return 0
        n = self._stage(entries)
        if sync:
            self._eng.sync()
        return n

    async def append_entries_async(self, entries: list[LogEntry],
                                   sync: bool = True) -> int:
        """LogManager hook: stage inline (ctypes releases the GIL for
        the buffered write — no executor hop), then join the engine-wide
        group commit — N groups flushing concurrently cost ONE fsync."""
        if not entries:
            return 0
        n = self._stage(entries)
        if sync:
            await self._eng.group_commit.flush()
        return n

    def truncate_prefix(self, first_index_kept: int) -> None:
        if self._lib.tlm_truncate_prefix(self._eng._h, self._gid,
                                         first_index_kept) != 0:
            raise IOError("multilog truncate_prefix failed")
        self._eng.gc()  # opportunistic: drop fully-dead journal files

    def truncate_suffix(self, last_index_kept: int) -> None:
        if self._lib.tlm_truncate_suffix(self._eng._h, self._gid,
                                         last_index_kept) != 0:
            raise IOError("multilog truncate_suffix failed")

    def reset(self, next_log_index: int) -> None:
        if self._lib.tlm_reset(self._eng._h, self._gid, next_log_index) != 0:
            raise IOError("multilog reset failed")

    def configuration_indexes(self) -> list[int]:
        n = self._lib.tlm_conf_count(self._eng._h, self._gid)
        if n == 0:
            return []
        buf = (ctypes.c_int64 * n)()
        got = self._lib.tlm_conf_indexes(self._eng._h, self._gid, buf, n)
        return list(buf[:got])
