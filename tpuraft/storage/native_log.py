"""ctypes bindings for the C++ log storage engine (native/logstore.cc).

Reference parity: the JNI seam under ``core:storage/impl/RocksDBLogStorage``
— Java orchestrates, C++ owns the bytes (SURVEY.md §3.4).  Here Python
encodes/decodes :class:`LogEntry` (one codec shared with FileLogStorage)
and the C++ engine owns segments, recovery scan, CRC verification, fsync
batching and truncation.  Same on-disk format as FileLogStorage — the two
are interchangeable on one directory.

Build: ``make -C native`` (g++ + zlib only).  :func:`ensure_built` does it
on demand for tests/dev.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Optional

from tpuraft.entity import LogEntry
from tpuraft.storage.log_storage import LogStorage

_FRAME = struct.Struct("<I")
_LIB_NAME = "libtpuraft_logstore.so"


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")


def lib_path() -> str:
    return os.environ.get(
        "TPURAFT_NATIVE_LIB", os.path.join(_native_dir(), _LIB_NAME))


def ensure_built(timeout: float = 120.0) -> str:
    from tpuraft.util.native_build import ensure_built as _eb
    return _eb(_native_dir(), lib_path(), timeout=timeout)


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(lib_path())
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tls_open.restype = ctypes.c_void_p
            lib.tls_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char_p, ctypes.c_int]
            lib.tls_close.argtypes = [ctypes.c_void_p]
            lib.tls_first_index.restype = ctypes.c_int64
            lib.tls_first_index.argtypes = [ctypes.c_void_p]
            lib.tls_last_index.restype = ctypes.c_int64
            lib.tls_last_index.argtypes = [ctypes.c_void_p]
            lib.tls_get.restype = ctypes.c_int64
            lib.tls_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.POINTER(u8p)]
            lib.tls_free.argtypes = [u8p]
            lib.tls_append.restype = ctypes.c_int64
            lib.tls_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int64, ctypes.c_int,
                                       ctypes.c_char_p, ctypes.c_int]
            lib.tls_truncate_prefix.restype = ctypes.c_int
            lib.tls_truncate_prefix.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.tls_truncate_suffix.restype = ctypes.c_int
            lib.tls_truncate_suffix.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.tls_reset.restype = ctypes.c_int
            lib.tls_reset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.tls_conf_count.restype = ctypes.c_int64
            lib.tls_conf_count.argtypes = [ctypes.c_void_p]
            lib.tls_conf_indexes.restype = ctypes.c_int64
            lib.tls_conf_indexes.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64]
            _lib = lib
        return _lib


class NativeLogStorage(LogStorage):
    """LogStorage over the C++ engine; selected by ``native://<dir>``."""

    def __init__(self, dir_path: str, segment_max_bytes: int | None = None):
        self._dir = dir_path
        self._seg_max = segment_max_bytes or 0  # 0 -> engine default (64MB)
        self._h: Optional[int] = None
        self._lib = _load()

    def init(self) -> None:
        # the C engine mkdirs only the leaf; create parents here so the
        # scheme doesn't depend on a sibling store initializing first
        parent = os.path.dirname(self._dir.rstrip("/"))
        if parent:
            os.makedirs(parent, exist_ok=True)
        err = ctypes.create_string_buffer(256)
        h = self._lib.tls_open(self._dir.encode(), self._seg_max, err, 256)
        if not h:
            raise IOError(f"native log open failed: {err.value.decode()}")
        self._h = h

    def shutdown(self) -> None:
        if self._h is not None:
            self._lib.tls_close(self._h)
            self._h = None

    def first_log_index(self) -> int:
        return self._lib.tls_first_index(self._h)

    def last_log_index(self) -> int:
        return self._lib.tls_last_index(self._h)

    def get_entry(self, index: int) -> Optional[LogEntry]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.tls_get(self._h, index, ctypes.byref(out))
        if n < 0:
            return None
        try:
            blob = ctypes.string_at(out, n)
        finally:
            self._lib.tls_free(out)
        return LogEntry.decode(blob)

    def append_entries(self, entries: list[LogEntry], sync: bool = True) -> int:
        if not entries:
            return 0
        parts = []
        for e in entries:
            blob = e.encode()
            parts.append(_FRAME.pack(len(blob)))
            parts.append(blob)
        frames = b"".join(parts)
        err = ctypes.create_string_buffer(256)
        n = self._lib.tls_append(self._h, frames, len(frames),
                                 1 if sync else 0, err, 256)
        if n < 0:
            raise ValueError(f"native append failed: {err.value.decode()}")
        return n

    def truncate_prefix(self, first_index_kept: int) -> None:
        if self._lib.tls_truncate_prefix(self._h, first_index_kept) != 0:
            raise IOError("native truncate_prefix failed")

    def truncate_suffix(self, last_index_kept: int) -> None:
        if self._lib.tls_truncate_suffix(self._h, last_index_kept) != 0:
            raise IOError("native truncate_suffix failed")

    def reset(self, next_log_index: int) -> None:
        if self._lib.tls_reset(self._h, next_log_index) != 0:
            raise IOError("native reset failed")

    def configuration_indexes(self) -> list[int]:
        n = self._lib.tls_conf_count(self._h)
        if n == 0:
            return []
        buf = (ctypes.c_int64 * n)()
        got = self._lib.tls_conf_indexes(self._h, buf, n)
        return list(buf[:got])
