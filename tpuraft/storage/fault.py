"""Crash-consistency fault injection for the storage plane.

Mirrors what ``rpc/fault.py`` + ``util/nemesis.py`` do for the network:
an injection surface for the *durability* faults the advisor keeps
finding by inspection (torn tails, lost fsyncs, registry/journal
ordering) — made mechanically reproducible.  Two layers:

``ChaosDir`` + ``FaultInjectingFile``
    Live interposition over the Python storage planes (FileLogStorage,
    MetaJournal, snapshots): while installed, every ``open``/``os.fsync``
    /``os.replace``/``os.remove`` under the tracked root is observed and
    the *proven-durable* content of each file is modeled in memory
    (bytes covered by a completed fsync).  "Simulate power loss now"
    materializes the durable-only image, with seeded injections in the
    unsynced suffix:

    - **lost fsync**    buffered-but-unsynced bytes discarded entirely
    - **torn write**    a random prefix of the unsynced suffix survives
                        (can cut mid-record — CRC framing must catch it)
    - **short write**   cut at a write-op boundary plus a partial op
    - **bit flip**      the suffix survives with one bit corrupted
                        (partial-page writeback garbage)
    - **writeback-all** everything survives (the lucky crash)

    All injections stay in the *unsynced* region: that is what a real
    power loss can legally do.  Corrupting fsynced bytes is a different
    fault class (bit rot) and must fail loudly (CorruptLogError), never
    be silently truncated — tests cover it separately.

``NativeJournalTracker``
    The native multilog engine (native/multilog.cc) does fd-level I/O
    in C++, out of reach of Python interposition.  Its durable floor is
    still externally observable: staged bytes hit the fd immediately
    (plain ``write``), so journal file sizes captured *immediately
    after a tlm_sync round* are exactly the proven-durable bytes, and
    rotation fsyncs outgoing files (only the newest journal and the
    ``groups`` registry can carry an unsynced tail).  ``crash_image``
    copies the live directory and applies the same injection menu to
    those tails.

Model simplifications (documented, deliberate):
  - deletes and directory renames are applied durably at once (the
    interesting hazards here are content-level, and every rename in the
    storage plane is followed by a directory fsync);
  - a rename whose source was never fsynced may materialize the
    destination EMPTY at crash (rename durable, content not) or keep
    the old destination (rename lost) — both legal, both injected.
"""

from __future__ import annotations

import builtins
import errno
import os
import random
import shutil
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Optional

# injection menu: (mode, default weight)
CRASH_MODES: tuple[tuple[str, float], ...] = (
    ("lost-fsync", 0.30),
    ("torn-write", 0.30),
    ("short-write", 0.15),
    ("bit-flip", 0.10),
    ("writeback-all", 0.15),
)


def _pick_mode(rng, modes=CRASH_MODES) -> str:
    names = [m for m, _ in modes]
    weights = [w for _, w in modes]
    return rng.choices(names, weights=weights)[0]


def _flip_bit(blob: bytes, lo: int, rng) -> bytes:
    """Flip one random bit at offset >= lo (no-op if the region is empty)."""
    if lo >= len(blob):
        return blob
    i = rng.randrange(lo, len(blob))
    b = bytearray(blob)
    b[i] ^= 1 << rng.randrange(8)
    return bytes(b)


def _injected_suffix(durable: bytes, live: bytes, boundaries: list[int],
                     rng, modes=CRASH_MODES) -> tuple[bytes, str]:
    """Choose what survives of ``live`` given ``durable`` is proven.

    Requires durable to be a prefix of live (the append-only common
    case); callers handle the rewrite case separately.
    """
    mode = _pick_mode(rng, modes)
    d = len(durable)
    if mode == "lost-fsync":
        return durable, mode
    if mode == "writeback-all":
        return live, mode
    if mode == "bit-flip":
        return _flip_bit(live, d, rng), mode
    if mode == "short-write":
        # cut at a recorded write-op boundary, then a partial op
        past = [b for b in boundaries if b > d]
        if past:
            start = rng.choice([d] + past[:-1])
            end = min((b for b in past if b > start), default=len(live))
            cut = rng.randrange(start, end + 1)
            return live[:cut], mode
        mode = "torn-write"
    # torn-write: any byte of the suffix
    cut = rng.randrange(d, len(live) + 1)
    return live[:cut], mode


# ---------------------------------------------------------------------------
# live interposition (Python storage planes)
# ---------------------------------------------------------------------------


@dataclass
class _PathState:
    """Durable model of one tracked file."""

    durable: bytes = b""
    # end offsets of write ops since the last fsync (short-write cuts);
    # bounded — old boundaries matter less than recent ones
    boundaries: list = field(default_factory=list)
    min_dirty: int = 1 << 62      # lowest offset written since last fsync
    ever_synced: bool = False
    # pre-rename durable content of this path (rename-lost outcome)
    prev: Optional[bytes] = None

    def note_write(self, pos: int, end: int) -> None:
        self.min_dirty = min(self.min_dirty, pos)
        self.boundaries.append(end)
        if len(self.boundaries) > 64:
            del self.boundaries[0]

    def clear_dirty(self) -> None:
        self.boundaries.clear()
        self.min_dirty = 1 << 62
        self.prev = None


class FaultInjectingFile:
    """Transparent file proxy that reports writes/truncates to its
    :class:`ChaosDir`.  Everything else delegates to the real file."""

    def __init__(self, real, path: str, owner: "ChaosDir"):
        self._real = real
        self._path = path
        self._owner = owner

    # -- write-path interceptions -------------------------------------------

    def write(self, data):
        self._owner._slow_sleep("write")
        pos = self._real.tell()
        admitted = self._owner._quota_admit(self._path, pos, len(data))
        if admitted < len(data):
            # partial write at the quota boundary: a real disk commits
            # what fit before returning the short count / ENOSPC, so the
            # admitted prefix LANDS (and is modeled) before the error
            if admitted > 0:
                self._real.write(bytes(memoryview(data)[:admitted]))
                self._owner._note_write(self._path, pos, pos + admitted)
            raise OSError(errno.ENOSPC,
                          f"no space left on device (chaos quota): "
                          f"{self._path}")
        n = self._real.write(data)
        self._owner._note_write(self._path, pos, pos + len(data))
        return n

    def truncate(self, size=None):
        try:
            pre = os.path.getsize(self._path)
        except OSError:
            pre = 0
        r = self._real.truncate(size)
        new = self._real.tell() if size is None else size
        self._owner._quota_refund(pre - new)
        self._owner._note_truncate(self._path, new)
        return r

    def close(self):
        self._owner._note_close(self)
        return self._real.close()

    # -- passthrough ---------------------------------------------------------

    def fileno(self):
        return self._real.fileno()

    def __getattr__(self, name):
        return getattr(self._real, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        return iter(self._real)


class _Interposer:
    """Process-wide patch of open/os.* that dispatches tracked paths to
    their owning ChaosDir.  Installed while >= 1 ChaosDir is active."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self._dirs: dict[str, "ChaosDir"] = {}   # root -> owner
        self._fds: dict[int, object] = {}        # fd -> wrapper | "dir"
        self._real: dict[str, object] = {}

    # -- root registry -------------------------------------------------------

    def add(self, cd: "ChaosDir") -> None:
        with self.lock:
            if cd.root in self._dirs:
                raise ValueError(f"ChaosDir already active for {cd.root}")
            first = not self._dirs
            self._dirs[cd.root] = cd
            if first:
                self._install()

    def remove(self, cd: "ChaosDir") -> None:
        with self.lock:
            if cd.root not in self._dirs or self._dirs[cd.root] is not cd:
                return  # idempotent: double-uninstall must be harmless
            del self._dirs[cd.root]
            kept = {}
            for fd, w in self._fds.items():
                ent = w() if isinstance(w, weakref.ref) else w
                if ent is None:
                    continue  # wrapper GC'd: drop the stale entry
                if getattr(ent, "_owner", None) is cd:
                    continue
                kept[fd] = w
            self._fds = kept
            if not self._dirs:
                self._uninstall()

    def owner(self, path) -> Optional["ChaosDir"]:
        try:
            p = os.path.abspath(os.fspath(path))
        except TypeError:
            return None
        with self.lock:
            for root, cd in self._dirs.items():
                if p == root or p.startswith(root + os.sep):
                    return cd
        return None

    # -- patch plumbing ------------------------------------------------------

    def _install(self) -> None:
        self._real = {
            "open": builtins.open,
            "os_open": os.open,
            "os_close": os.close,
            "fsync": os.fsync,
            "replace": os.replace,
            "rename": os.rename,
            "remove": os.remove,
            "unlink": os.unlink,
        }
        builtins.open = self._open          # type: ignore[assignment]
        os.open = self._os_open             # type: ignore[assignment]
        os.close = self._os_close           # type: ignore[assignment]
        os.fsync = self._fsync              # type: ignore[assignment]
        os.replace = self._replace          # type: ignore[assignment]
        os.rename = self._rename            # type: ignore[assignment]
        os.remove = self._remove            # type: ignore[assignment]
        os.unlink = self._remove            # type: ignore[assignment]

    def _uninstall(self) -> None:
        builtins.open = self._real["open"]  # type: ignore[assignment]
        os.open = self._real["os_open"]     # type: ignore[assignment]
        os.close = self._real["os_close"]   # type: ignore[assignment]
        os.fsync = self._real["fsync"]      # type: ignore[assignment]
        os.replace = self._real["replace"]  # type: ignore[assignment]
        os.rename = self._real["rename"]    # type: ignore[assignment]
        os.remove = self._real["remove"]    # type: ignore[assignment]
        os.unlink = self._real["unlink"]    # type: ignore[assignment]
        self._fds.clear()
        # _real is deliberately KEPT: a thread already inside a patched
        # dispatcher (past its lock) still needs self._real[...] — the
        # retained entries are the genuine os/builtins functions, so a
        # late call through them is exactly a real call.  The next
        # install() overwrites them from the (restored) live bindings.

    def real_open(self, *a, **kw):
        return (self._real.get("open") or builtins.open)(*a, **kw)

    # -- dispatchers ----------------------------------------------------------

    def _open(self, file, mode="r", *a, **kw):
        owner = None
        if isinstance(file, (str, bytes, os.PathLike)) \
                and not isinstance(file, bytes) and "b" in mode:
            owner = self.owner(file)
        pre = None
        if owner is not None:
            # snapshot BEFORE the real open (a "w" mode truncates, but
            # the old content stays durable until the next fsync)...
            pre = owner._pre_open(os.path.abspath(os.fspath(file)))
        f = self._real["open"](file, mode, *a, **kw)
        if owner is None:
            return f
        path = os.path.abspath(os.fspath(file))
        wrapped = FaultInjectingFile(f, path, owner)
        with self.lock:
            # weakref: a wrapper abandoned without close() (the
            # open(...).read() idiom) must not pin its fd for the whole
            # interposition lifetime — GC closes the real file, and the
            # dead entry is dropped at next lookup
            self._fds[f.fileno()] = weakref.ref(wrapped)
        # ...and only register state once the open SUCCEEDED: a failed
        # probe of a missing file must not leave phantom model state
        # that a later crash would materialize as an empty file
        owner._post_open(path, pre)
        return wrapped

    def _os_open(self, path, flags, *a, **kw):
        fd = self._real["os_open"](path, flags, *a, **kw)
        try:
            owner = self.owner(path)
            if owner is not None and os.path.isdir(path):
                with self.lock:
                    self._fds[fd] = ("dir", owner,
                                     os.path.abspath(os.fspath(path)))
        except Exception:
            pass
        return fd

    def _os_close(self, fd):
        with self.lock:
            self._fds.pop(fd, None)
        return self._real["os_close"](fd)

    def _fsync(self, fd):
        with self.lock:
            ent = self._fds.get(fd)
            if isinstance(ent, weakref.ref):
                ent = ent()
                if ent is None:
                    self._fds.pop(fd, None)  # wrapper GC'd; fd reused
        if ent is None:
            return self._real["fsync"](fd)
        if isinstance(ent, tuple):  # ("dir", owner, path)
            # a completed directory fsync COMMITS renames/creates in it:
            # the rename-lost crash outcome is only legal before this
            ent[1]._note_dir_fsync(ent[2])
            return None
        # fail-slow injection: the sleep happens on the CALLING thread
        # (executor threads for the storage planes), exactly where a
        # real stalling disk would park it — durability modeling only
        # proceeds once the "disk" comes back
        ent._owner._slow_sleep("fsync")
        ent._owner._note_fsync(ent._path)
        return None      # modeled; skip the real (slow) fsync

    def _replace(self, src, dst, **kw):
        return self._renamish("replace", src, dst, **kw)

    def _rename(self, src, dst, **kw):
        return self._renamish("rename", src, dst, **kw)

    def _renamish(self, which, src, dst, **kw):
        owner = self.owner(dst) or self.owner(src)
        freed = 0
        if owner is not None:
            owner._quota_admit_rename(os.path.abspath(os.fspath(src)),
                                      os.path.abspath(os.fspath(dst)))
            try:  # replacing an existing file frees its bytes
                if os.path.isfile(dst):
                    freed = os.path.getsize(dst)
            except OSError:
                pass
        r = self._real[which](src, dst, **kw)
        if owner is not None:
            owner._quota_refund(freed)
            owner._note_replace(os.path.abspath(os.fspath(src)),
                                os.path.abspath(os.fspath(dst)))
        return r

    def _remove(self, path, **kw):
        owner = self.owner(path)
        freed = 0
        if owner is not None:
            try:
                freed = os.path.getsize(path)
            except OSError:
                pass
        r = self._real["remove"](path, **kw)
        if owner is not None:
            owner._quota_refund(freed)
            owner._note_remove(os.path.abspath(os.fspath(path)))
        return r


_INTERPOSER = _Interposer()


class ChaosDir:
    """Durable-state model + power-loss materialization for one
    directory tree of Python-side storage files.

    Use as a context manager (or ``install()``/``uninstall()``) around
    the storage objects' lifetime — files must be opened while the
    interposition is active to be tracked.  Pre-existing files are
    snapshot as fully durable at install time.
    """

    def __init__(self, root: str, modes=CRASH_MODES):
        self.root = os.path.abspath(root)
        self.modes = modes
        self._lock = threading.RLock()
        self._files: dict[str, _PathState] = {}
        self.crash_count = 0
        self.injected: dict[str, int] = {}
        # -- fail-slow injection (gray failures) -----------------------------
        # per-call latency for fsync/write under this root, plus a full
        # fsync hang: a stalling disk keeps the store "alive" to every
        # liveness check while everything it leads limps.  Sleeps run on
        # the CALLING thread (see _Interposer._fsync) — the executor
        # threads a real slow disk would park.  Seeded jitter keeps
        # drives replayable.
        self._slow_fsync_ms = 0.0      # guarded-by: _lock
        self._slow_write_ms = 0.0      # guarded-by: _lock
        self._slow_jitter_ms = 0.0     # guarded-by: _lock
        self._slow_rng = random.Random(0)  # guarded-by: _lock
        # open = fsyncs proceed; cleared by stall_fsync() so every fsync
        # under this root BLOCKS until heal_slow() (the hung-disk mode)
        self._fsync_gate = threading.Event()
        self._fsync_gate.set()
        self.slow_counts: dict[str, int] = {}
        # -- capacity faults (ENOSPC) ----------------------------------------
        # byte budget across the tree, charged at write/append/rename;
        # once exceeded writes fail ENOSPC with the fitting prefix
        # committed (real short writes).  Usage is tracked by extension
        # bytes and lazily re-based from the live tree — deletes that
        # bypass the interposer (shutil.rmtree) are picked up on the
        # next over-budget admission, which is how reclaim un-wedges a
        # full store without an explicit refund hook.
        self._quota_limit: Optional[int] = None   # guarded-by: _lock
        self._quota_used = 0                      # guarded-by: _lock
        self._quota_refreshed = 0.0               # guarded-by: _lock
        self._burst_rate = 0.0                    # guarded-by: _lock
        self._burst_rng = random.Random(0)        # guarded-by: _lock
        self.enospc_counts: dict[str, int] = {}   # guarded-by: _lock

    # -- lifecycle ------------------------------------------------------------

    def install(self) -> "ChaosDir":
        os.makedirs(self.root, exist_ok=True)
        with self._lock:
            for dirpath, _dirs, names in os.walk(self.root):
                for n in names:
                    p = os.path.join(dirpath, n)
                    st = self._files.setdefault(p, _PathState())
                    st.durable = self._read_live(p)
                    st.ever_synced = True
        _INTERPOSER.add(self)
        return self

    def uninstall(self) -> None:
        # release any thread parked on a stalled fsync: a leaked stall
        # would wedge executor threads past the chaos drive's lifetime
        self.heal_slow()
        _INTERPOSER.remove(self)

    def __enter__(self) -> "ChaosDir":
        return self.install()

    # -- fail-slow injection (gray failures) ---------------------------------

    def set_slow(self, fsync_ms: float = 0.0, write_ms: float = 0.0,
                 jitter_ms: float = 0.0, seed: int = 0) -> None:
        """Per-call latency: every fsync/write under the root sleeps
        ``base + uniform(0, jitter)`` ms on its calling thread.  Use a
        high fsync_ms for a burst disk stall, moderate values for the
        sustained slow-disk mode; ``heal_slow`` clears everything."""
        with self._lock:
            self._slow_fsync_ms = fsync_ms
            self._slow_write_ms = write_ms
            self._slow_jitter_ms = jitter_ms
            self._slow_rng = random.Random(seed)

    def stall_fsync(self) -> None:
        """Full fsync hang: every fsync under the root BLOCKS (on its
        calling thread) until :meth:`heal_slow`.  The worst gray
        failure — writes buffer, nothing durably completes, the store
        answers everything that needs no disk."""
        self._fsync_gate.clear()

    def heal_slow(self) -> None:
        """Clear all latency faults and release stalled fsyncs."""
        with self._lock:
            self._slow_fsync_ms = 0.0
            self._slow_write_ms = 0.0
            self._slow_jitter_ms = 0.0
        self._fsync_gate.set()

    def _slow_sleep(self, kind: str) -> None:
        """Dispatcher hook (interposer fsync / wrapped write): apply the
        configured latency OUTSIDE the model lock — sleeping under it
        would stall event-loop readers behind the fake disk."""
        if kind == "fsync" and not self._fsync_gate.is_set():
            with self._lock:
                self.slow_counts["fsync_stalled"] = \
                    self.slow_counts.get("fsync_stalled", 0) + 1
            self._fsync_gate.wait()
            return
        with self._lock:
            base = self._slow_fsync_ms if kind == "fsync" \
                else self._slow_write_ms
            if base <= 0:
                return  # jitter rides a configured base, never alone
            delay = base
            if self._slow_jitter_ms > 0:
                delay += self._slow_rng.uniform(0.0, self._slow_jitter_ms)
            self.slow_counts[f"{kind}_slowed"] = \
                self.slow_counts.get(f"{kind}_slowed", 0) + 1
        time.sleep(delay / 1000.0)

    # -- capacity faults (ENOSPC) --------------------------------------------

    def set_quota(self, limit_bytes: int) -> None:
        """Byte budget for the whole tree: current usage is measured
        now, and any write/append/rename that would grow the tree past
        the budget fails ENOSPC — with the fitting prefix of the write
        committed first (real disks do short writes at the boundary).
        Overwrites within a file's current size are free."""
        with self._lock:
            self._quota_limit = max(0, int(limit_bytes))
            self._quota_used = self._disk_usage_locked()
            self._quota_refreshed = time.monotonic()

    def shrink_quota(self, delta_bytes: int) -> int:
        """Tighten the budget by ``delta_bytes`` (quota-shrink-over-time
        nemesis); returns the new limit.  No-op without a quota."""
        with self._lock:
            if self._quota_limit is None:
                return 0
            self._quota_limit = max(0, self._quota_limit - int(delta_bytes))
            return self._quota_limit

    def clear_quota(self) -> None:
        """Lift the byte budget (bursts configured separately)."""
        with self._lock:
            self._quota_limit = None

    def set_enospc_burst(self, rate: float, seed: int = 0) -> None:
        """Seeded intermittent ENOSPC: each write/rename under the root
        independently fails with probability ``rate`` (whole-op, no
        partial).  ``rate=0`` heals.  Models transient quota races /
        reservation failures rather than a genuinely full disk."""
        with self._lock:
            self._burst_rate = max(0.0, float(rate))
            self._burst_rng = random.Random(seed)

    def quota_state(self) -> tuple[Optional[int], int]:
        """(limit, used-estimate) snapshot for assertions/telemetry."""
        with self._lock:
            return self._quota_limit, self._quota_used

    def _disk_usage_locked(self) -> int:
        total = 0
        for dirpath, _dirs, names in os.walk(self.root):
            for n in names:
                try:
                    total += os.path.getsize(os.path.join(dirpath, n))
                except OSError:
                    pass
        return total

    def _refresh_quota_used_locked(self) -> None:
        # re-base from the live tree (rate-limited: this runs on every
        # over-budget admission, and full stores see write storms)
        now = time.monotonic()
        if now - self._quota_refreshed < 0.05:
            return
        self._quota_refreshed = now
        self._quota_used = self._disk_usage_locked()

    def _quota_admit(self, path: str, pos: int, n: int) -> int:
        """How many of the ``n`` bytes at ``pos`` may land (wrapped-file
        write hook).  Charges only extension bytes past the file's
        current size; returns ``n`` when unconstrained."""
        with self._lock:
            if self._burst_rate > 0.0 \
                    and self._burst_rng.random() < self._burst_rate:
                self.enospc_counts["burst"] = \
                    self.enospc_counts.get("burst", 0) + 1
                return 0
            if self._quota_limit is None:
                return n
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            ext = pos + n - size
            if ext <= 0:
                return n
            free = self._quota_limit - self._quota_used
            if ext > free:
                # maybe stale: reclaim deletes (rmtree) bypass the
                # interposer — re-measure before refusing
                self._refresh_quota_used_locked()
                free = self._quota_limit - self._quota_used
            if ext <= free:
                self._quota_used += ext
                return n
            self.enospc_counts["write"] = \
                self.enospc_counts.get("write", 0) + 1
            fits = max(0, free)
            self._quota_used += fits
            return n - (ext - fits)

    def _quota_refund(self, nbytes: int) -> None:
        """Bytes freed by a tracked remove/truncate/replace-overwrite.
        (rmtree deletes bypass the interposer and are picked up by the
        lazy re-measure in :meth:`_quota_admit` instead.)"""
        if nbytes <= 0:
            return
        with self._lock:
            if self._quota_limit is not None:
                self._quota_used = max(0, self._quota_used - nbytes)

    def _quota_admit_rename(self, src: str, dst: str) -> None:
        """Pre-op gate for rename/replace under the root: creating a
        fresh directory entry on a full disk fails ENOSPC (and bursts
        hit renames too — meta compaction / snapshot commit exercise
        their failure paths)."""
        with self._lock:
            key = None
            if self._burst_rate > 0.0 \
                    and self._burst_rng.random() < self._burst_rate:
                key = "burst"
            elif self._quota_limit is not None \
                    and not os.path.lexists(dst):
                if self._quota_used >= self._quota_limit:
                    self._refresh_quota_used_locked()
                if self._quota_used >= self._quota_limit:
                    key = "rename"
            if key is not None:
                self.enospc_counts[key] = self.enospc_counts.get(key, 0) + 1
                raise OSError(errno.ENOSPC,
                              f"no space left on device (chaos quota): "
                              f"rename to {dst}")

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- observation hooks (called by the interposer) -------------------------

    def _pre_open(self, path: str) -> Optional[_PathState]:
        """Capture what an untracked existing file held before the real
        open can truncate it (it was durable before we ever saw it);
        returns the state to register IF the open succeeds."""
        with self._lock:
            st = self._files.get(path)
            if st is not None:
                if os.path.exists(path):
                    return st
                # deleted behind our back (shutil.rmtree uses dir_fd-
                # relative unlinks that bypass the path dispatch):
                # deletions are modeled durable, so a recreation at the
                # same path starts FRESH — carrying the stale durable
                # content forward would let a crash roll the new file
                # back to a dead epoch (an illegal image: e.g. an old
                # kv_data inside a newly committed snapshot)
                self._files.pop(path, None)
            st = _PathState()
            if os.path.exists(path):
                st.durable = self._read_live(path)
                st.ever_synced = True
            return st

    def _post_open(self, path: str, st: Optional[_PathState]) -> None:
        with self._lock:
            if st is not None:
                self._files.setdefault(path, st)

    def _note_write(self, path: str, pos: int, end: int) -> None:
        with self._lock:
            self._files.setdefault(path, _PathState()).note_write(pos, end)

    def _note_truncate(self, path: str, size: int) -> None:
        # live view changed; durability unchanged until the next fsync —
        # but the dirty frontier must drop so that fsync re-reads from
        # the truncation point, not past stale durable bytes
        with self._lock:
            st = self._files.get(path)
            if st is not None:
                st.min_dirty = min(st.min_dirty, size)

    def _note_fsync(self, path: str) -> None:
        with self._lock:
            st = self._files.setdefault(path, _PathState())
            # delta read from the dirty frontier: journals grow by
            # appending, and re-reading the whole file per fsync would
            # make a long soak O(n^2) in file size
            lo = min(st.min_dirty, len(st.durable))
            if lo <= 0:
                st.durable = self._read_live(path)
            else:
                st.durable = st.durable[:lo] + self._read_live(path, lo)
            st.ever_synced = True
            st.clear_dirty()

    def _note_dir_fsync(self, dir_path: str) -> None:
        with self._lock:
            for p, st in self._files.items():
                if os.path.dirname(p) == dir_path:
                    st.prev = None

    def _note_close(self, wrapped: FaultInjectingFile) -> None:
        try:
            fd = wrapped._real.fileno()
        except ValueError:
            return
        with _INTERPOSER.lock:
            _INTERPOSER._fds.pop(fd, None)

    def _note_replace(self, src: str, dst: str) -> None:
        with self._lock:
            if os.path.isdir(dst):
                # directory rename (snapshot commit): re-key children;
                # modeled immediately durable (commit fsyncs the root)
                moved = [p for p in self._files
                         if p == src or p.startswith(src + os.sep)]
                for p in moved:
                    self._files[dst + p[len(src):]] = self._files.pop(p)
                return
            sst = self._files.pop(src, None)
            old = self._files.get(dst)
            st = _PathState()
            # rename itself is modeled durable, but the CONTENT carried
            # over is only what was fsynced of src; the old destination
            # durable content is kept as the rename-lost outcome
            st.durable = sst.durable if sst is not None else b""
            st.ever_synced = True
            st.prev = old.durable if old is not None and old.ever_synced \
                else None
            self._files[dst] = st

    def _note_remove(self, path: str) -> None:
        with self._lock:
            self._files.pop(path, None)

    # -- durable image --------------------------------------------------------

    def _read_live(self, path: str, offset: int = 0) -> bytes:
        with _INTERPOSER.lock:
            ropen = _INTERPOSER.real_open
        try:
            with ropen(path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read()
        except FileNotFoundError:
            return b""

    def capture_crash(self, rng) -> dict[str, Optional[bytes]]:
        """Decide the power-loss outcome NOW (reads live bytes, applies
        the seeded injection menu); returns {path: surviving_bytes or
        None-for-deleted}.  Apply later with :meth:`apply_crash` — the
        split lets a caller capture at the crash instant, cleanly shut
        the store down, then discard everything the shutdown wrote."""
        plan: dict[str, Optional[bytes]] = {}
        with self._lock:
            for path, st in sorted(self._files.items()):
                if not os.path.exists(path):
                    # deleted behind our back (dir_fd-relative unlink):
                    # deletion is modeled durable — stays deleted
                    plan[path] = None
                    continue
                live = self._read_live(path)
                if st.prev is not None and rng.random() < 0.25:
                    chosen, mode = st.prev, "rename-lost"
                elif live == st.durable:
                    chosen, mode = live, "stable"
                elif st.durable == live[:len(st.durable)]:
                    chosen, mode = _injected_suffix(
                        st.durable, live, st.boundaries, rng, self.modes)
                else:
                    # rewrite/truncate in flight: old or new image
                    chosen = st.durable if rng.random() < 0.5 else live
                    mode = "old-or-new"
                if chosen == b"" and not st.ever_synced \
                        and rng.random() < 0.5:
                    plan[path] = None  # never-synced create: may vanish
                    mode = "unlinked"
                else:
                    plan[path] = chosen
                if mode not in ("stable",):
                    self.injected[mode] = self.injected.get(mode, 0) + 1
        return plan

    def apply_crash(self, plan: dict[str, Optional[bytes]]) -> None:
        """Materialize a captured power-loss image in place and reset
        the durable model to it (surviving bytes are re-proven by the
        recovery fsync discipline on reopen)."""
        with self._lock, _INTERPOSER.lock:
            ropen = _INTERPOSER.real_open
            remove = _INTERPOSER._real.get("remove", os.remove)
            # files created after the capture died with the power
            for path in list(self._files):
                if path not in plan:
                    self._files.pop(path, None)
                    try:
                        remove(path)
                    except FileNotFoundError:
                        pass
            for path, blob in plan.items():
                st = self._files.setdefault(path, _PathState())
                if blob is None:
                    self._files.pop(path, None)
                    try:
                        remove(path)
                    except FileNotFoundError:
                        pass
                    continue
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with ropen(path, "wb") as f:
                    f.write(blob)
                st.durable = blob
                st.ever_synced = True
                st.clear_dirty()
            self.crash_count += 1

    def crash(self, rng) -> dict[str, Optional[bytes]]:
        """capture + apply in one step (power loss right now)."""
        plan = self.capture_crash(rng)
        self.apply_crash(plan)
        return plan


# ---------------------------------------------------------------------------
# native multilog journal (C++ fd-level I/O — imaged, not interposed)
# ---------------------------------------------------------------------------


class NativeJournalTracker:
    """Externally tracks the durable floor of a native multilog dir.

    Call :meth:`note_sync` immediately after every ``tlm_sync`` round
    (before further appends): staged bytes are fd-visible, so the file
    sizes at that instant are exactly the proven-durable bytes.  Only
    the newest journal and the ``groups`` registry can carry an
    unsynced tail (rotation fsyncs outgoing files).
    """

    def __init__(self, dir_path: str, modes=CRASH_MODES):
        self.dir = dir_path
        self.modes = modes
        self.floors: dict[str, int] = {}
        # -- capacity mirror (ENOSPC) ----------------------------------------
        # the C++ fd writes are unpatachable, so the quota is enforced
        # one layer up: MultiLogStorage._stage consults the engine's
        # ``fault_gate`` before tlm_append.  Single-threaded per store
        # loop + engine lock upstream — no lock needed here.
        self._quota_limit: Optional[int] = None
        self._quota_used = 0
        self._burst_rate = 0.0
        self._burst_rng = random.Random(0)
        self.enospc_counts: dict[str, int] = {}

    # -- capacity faults (ENOSPC), mirroring ChaosDir ------------------------

    def attach_quota(self, engine, limit_bytes: Optional[int] = None,
                     burst_rate: float = 0.0, seed: int = 0) -> None:
        """Install this tracker as the engine's append fault gate (see
        ``MultiLogEngine.fault_gate``) with an optional byte budget over
        the journal dir and/or a seeded intermittent ENOSPC burst."""
        if limit_bytes is not None:
            self._quota_limit = max(0, int(limit_bytes))
            self._quota_used = self._dir_usage()
        self._burst_rate = max(0.0, float(burst_rate))
        self._burst_rng = random.Random(seed)
        engine.fault_gate = self.charge_append

    def clear_quota(self) -> None:
        self._quota_limit = None
        self._burst_rate = 0.0

    def _dir_usage(self) -> int:
        total = 0
        try:
            for n in os.listdir(self.dir):
                try:
                    total += os.path.getsize(os.path.join(self.dir, n))
                except OSError:
                    pass
        except OSError:
            pass
        return total

    def charge_append(self, nbytes: int) -> None:
        """Engine fault gate: account ``nbytes`` about to be staged and
        raise ENOSPC once the journal dir would exceed the budget (the
        native append is all-or-nothing, so no partial admission)."""
        if self._burst_rate > 0.0 \
                and self._burst_rng.random() < self._burst_rate:
            self.enospc_counts["burst"] = \
                self.enospc_counts.get("burst", 0) + 1
            raise OSError(errno.ENOSPC,
                          "no space left on device (chaos burst): "
                          f"{self.dir}")
        if self._quota_limit is None:
            return
        if self._quota_used + nbytes > self._quota_limit:
            # journal GC deletes files underneath us — re-measure
            # before refusing, so reclaim un-wedges the quota
            self._quota_used = self._dir_usage()
        if self._quota_used + nbytes > self._quota_limit:
            self.enospc_counts["append"] = \
                self.enospc_counts.get("append", 0) + 1
            raise OSError(errno.ENOSPC,
                          "no space left on device (chaos quota): "
                          f"{self.dir}")
        self._quota_used += nbytes

    def _journals(self, root: Optional[str] = None) -> list[str]:
        root = root or self.dir
        return sorted(n for n in os.listdir(root)
                      if n.startswith("journal_") and n.endswith(".log"))

    def note_sync(self) -> None:
        self.floors = {
            n: os.path.getsize(os.path.join(self.dir, n))
            for n in self._journals()}
        reg = os.path.join(self.dir, "groups")
        if os.path.exists(reg):
            self.floors["groups"] = os.path.getsize(reg)

    def crash_image(self, dst: str, rng) -> dict[str, str]:
        """Copy the live dir to ``dst`` and inject a power-loss outcome
        into the unsynced tails.  Returns {filename: mode}.  The live
        engine must be quiescent (no concurrent appends) for the copy
        to be a consistent instant."""
        if os.path.exists(dst):
            shutil.rmtree(dst)
        shutil.copytree(self.dir, dst)
        report: dict[str, str] = {}
        names = self._journals(dst)
        for i, n in enumerate(names):
            path = os.path.join(dst, n)
            size = os.path.getsize(path)
            if i < len(names) - 1:
                # rotation fsyncs outgoing files: fully durable even if
                # the floor snapshot predates the rotation
                continue
            floor = min(self.floors.get(n, 0), size)
            report[n] = self._tear(path, floor, rng)
        reg = os.path.join(dst, "groups")
        if os.path.exists(reg):
            floor = min(self.floors.get("groups", 0),
                        os.path.getsize(reg))
            report["groups"] = self._tear(reg, floor, rng)
        return report

    def _tear(self, path: str, floor: int, rng) -> str:
        with open(path, "rb") as f:
            live = f.read()
        if len(live) <= floor:
            return "stable"
        chosen, mode = _injected_suffix(live[:floor], live, [], rng,
                                        self.modes)
        with open(path, "wb") as f:
            f.write(chosen)
        return mode
