"""L3 storage: durable raft log, meta, and snapshots.

Reference parity (SURVEY.md §3.1): ``core:storage/`` — LogStorage
(RocksDBLogStorage), LogManager (in-memory window + batched async flush),
LocalRaftMetaStorage, snapshot subsystem.  The file log storage here is a
segmented append log (the C++ native engine in ``native/`` implements the
same on-disk format; selected via ``log_uri`` scheme ``native://``).

Crash-consistency fault injection for all of it lives in
``tpuraft.storage.fault`` (ChaosDir / FaultInjectingFile /
NativeJournalTracker — see docs/operations.md "Crash-consistency
testing"); imported lazily, never on the serving path.
"""

from tpuraft.storage.log_storage import (
    LogStorage,
    MemoryLogStorage,
    FileLogStorage,
    create_log_storage,
)
from tpuraft.storage.meta_storage import RaftMetaStorage
from tpuraft.storage.log_manager import LogManager

__all__ = [
    "LogStorage",
    "MemoryLogStorage",
    "FileLogStorage",
    "create_log_storage",
    "RaftMetaStorage",
    "LogManager",
]
