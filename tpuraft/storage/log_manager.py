"""LogManager: in-memory log window + async batched stable storage.

Reference parity: ``core:storage/impl/LogManagerImpl`` (SURVEY.md §3.1,
§4.2) — the Disruptor + AppendBatcher pipeline becomes an asyncio flusher
task that coalesces concurrent appends into one storage write + fsync
(storage I/O runs in a thread executor so the event loop never blocks);
wait-listeners wake Replicators when the log grows; follower-side conflict
resolution (``#checkAndResolveConflict``) truncates divergent suffixes;
``#setSnapshot`` compacts the prefix after snapshots.

Single-writer discipline: all public methods must be called from the
node's event loop (the functional analog of LogManagerImpl's lock).
"""

from __future__ import annotations

import asyncio
from collections import deque
import logging
import time
from dataclasses import dataclass
from typing import Optional

from tpuraft.conf import ConfigurationEntry, ConfigurationManager
from tpuraft.entity import EntryType, LogEntry, LogId
from tpuraft.errors import RaftError, RaftException, Status
from tpuraft.util.trace import TRACER as _TRACE

LOG = logging.getLogger(__name__)


def _is_enospc(exc: BaseException) -> bool:
    import errno

    return getattr(exc, "errno", None) == errno.ENOSPC \
        or "ENOSPC" in str(exc) or "no space left" in str(exc).lower()


@dataclass
class _FlushReq:
    entries: list[LogEntry]
    future: asyncio.Future


# graftcheck: loop-confined — single-writer discipline (see module
# docstring): storage IO hops to executor threads, the manager's own
# state never does
class LogManager:
    def __init__(
        self,
        storage,
        conf_manager: Optional[ConfigurationManager] = None,
        sync: bool = True,
        max_flush_batch: int = 256,
        max_logs_in_memory: int = 256,
        max_logs_in_memory_bytes: int = 256 * 1024,
        health=None,
        trace_proc: str = "",
        disk_budget=None,
    ):
        self._storage = storage
        # capacity accounting: the store-level DiskBudget this flusher
        # feeds append bytes into (and ENOSPC observations — the
        # pressure ladder trusts the errno over its own estimate)
        self._disk_budget = disk_budget
        # trace-plane process identity for flush spans (the owning
        # node's store endpoint; "" for bare/legacy constructions)
        self._trace_proc = trace_proc or "log"
        # gray-failure signal: the store-level HealthTracker whose disk
        # probe this flusher times every flush round into (append +
        # fsync, executor queueing included — CPU saturation IS a gray
        # signal).  The probe's begin/end also exposes the AGE of a
        # still-in-flight flush, which is how a fully hung fsync is
        # detected (it never completes a sample).
        self._health = health
        self.conf_manager = conf_manager or ConfigurationManager()
        self._sync = sync
        self._max_flush_batch = max_flush_batch
        # retained recent window beyond stability/apply, so replication to
        # slightly-lagging followers is served from memory, not disk
        # (reference: LogManagerImpl's logsInMemory / maxLogsInMemory).
        # Both caps are per group; the bytes cap bounds multi-group RAM.
        self._max_in_memory = max_logs_in_memory
        self._max_in_memory_bytes = max_logs_in_memory_bytes

        self._mem: dict[int, LogEntry] = {}  # unstable + recent window
        self._mem_bytes = 0      # sum of len(e.data) over _mem
        self._trim_floor = 0     # all indexes <= this are trimmed from _mem
        self._first_index = 1
        self._last_index = 0          # includes unstable entries
        self._stable_index = 0        # flushed to storage
        self._applied_index = 0
        self._last_snapshot_id = LogId(0, 0)

        self._staged: list[LogEntry] = []
        self._stable_waiters: list[tuple[int, asyncio.Future]] = []
        # demand-spawned flusher (r4): a standing flush task per node is
        # O(nodes) idle tasks per process (48K at the 16Kx3 ladder rung);
        # requests queue here and one short-lived drain runs while any
        # exist.  Single-drainer + FIFO deque keeps flush order, which
        # _stable_index and the on_stable hook rely on.
        self._queue: deque = deque()
        self._inflight_flushes = 0
        self._flush_idle = asyncio.Event()
        self._flush_idle.set()
        self._flusher: Optional[asyncio.Task] = None
        self._waiters: list[tuple[int, asyncio.Future]] = []
        self._stopped = False
        # durable-advance hook: called with the new stable index after
        # every storage flush — the bridge that ships this replica's
        # (group, lastDurableIndex) into a replica-axis commit plane
        # (tpuraft.parallel.replica_plane; SURVEY §6 "ships (groupId,
        # peerId, lastLogIndex) tick-tensors ... into the JAX process")
        self.on_stable = None  # Optional[Callable[[int], None]]
        # storage-failure hook: called (with the exception) after a
        # flush round fails and its futures/waiters were failed — the
        # node maps this to leader step-down (clients get retryable
        # errors) instead of process death; see ISSUE 17 layer 4
        self.on_storage_error = None  # Optional[Callable[[BaseException], None]]

    # -- lifecycle ----------------------------------------------------------

    async def init(self) -> None:
        self._storage.init()
        self._first_index = self._storage.first_log_index()
        self._last_index = self._storage.last_log_index()
        self._stable_index = self._last_index
        # _mem is empty after init: everything recovered lives in storage,
        # so the incremental trim must start from the recovered tail (a
        # floor of 0 would make the first trim walk the whole log range)
        self._trim_floor = self._last_index
        # rebuild configuration history from the stored log (sidecar index:
        # O(#conf entries), not O(n) — see LogStorage#configuration_indexes).
        # Storages whose sidecar is an in-memory/C-side lookup advertise
        # CHEAP_CONF_INDEXES: the executor hop is pure overhead for them,
        # and at high group counts one hop per node serializes into tens
        # of seconds of boot (16K-groups ladder, VERDICT r3 #7).
        if getattr(self._storage, "CHEAP_CONF_INDEXES", False):
            conf_indexes = self._storage.configuration_indexes()
        else:
            loop = asyncio.get_running_loop()
            conf_indexes = await loop.run_in_executor(
                None, self._storage.configuration_indexes)
        for i in conf_indexes:
            e = self._storage.get_entry(i)
            if e and e.type == EntryType.CONFIGURATION:
                self._track_conf(e)

    async def shutdown(self) -> None:
        self._stopped = True
        if self._flusher is not None and not self._flusher.done():
            await self._flusher
        self._wake_waiters(error=True)
        self._storage.shutdown()

    # -- queries ------------------------------------------------------------

    def first_log_index(self) -> int:
        return self._first_index

    def last_log_index(self) -> int:
        return self._last_index

    def last_log_id(self) -> LogId:
        if self._last_index == self._last_snapshot_id.index:
            return self._last_snapshot_id
        return LogId(self._last_index, self.get_term(self._last_index))

    def last_snapshot_id(self) -> LogId:
        return self._last_snapshot_id

    def _mem_put(self, e) -> None:
        prev = self._mem.get(e.id.index)
        if prev is not None:
            self._mem_bytes -= len(prev.data)
        self._mem[e.id.index] = e
        self._mem_bytes += len(e.data)

    def _mem_pop(self, index: int) -> None:
        e = self._mem.pop(index, None)
        if e is not None:
            self._mem_bytes -= len(e.data)

    def get_entry(self, index: int) -> Optional[LogEntry]:
        if index > self._last_index or index < self._first_index:
            return None
        e = self._mem.get(index)
        if e is not None:
            return e
        return self._storage.get_entry(index)

    def get_term(self, index: int) -> int:
        if index == 0:
            return 0
        if index == self._last_snapshot_id.index:
            return self._last_snapshot_id.term
        e = self.get_entry(index)
        return e.id.term if e else 0

    def conflict_hint(self, prev_index: int,
                      prev_term: Optional[int] = None) -> int:
        """Start index of the term run containing ``prev_index`` in OUR
        log — returned to a leader whose prev-term probe mismatched, so
        its next probe skips the conflicting term run (classic Raft
        fast-backoff).  The walk only consults the in-memory window:
        this runs under the node lock on the event loop, so it must
        never fall through to storage reads.  A partial walk still
        returns a correct (just less aggressive) probe point; 0 = no
        hint."""
        t = prev_term if prev_term is not None else self.get_term(prev_index)
        if t == 0:
            return 0
        i = prev_index
        while i - 1 >= self._first_index:
            e = self._mem.get(i - 1)
            if e is None or e.id.term != t:
                break
            i -= 1
        return i

    def get_entries(self, from_index: int, max_count: int, max_bytes: int
                    ) -> list[LogEntry]:
        """Contiguous batch for replication, bounded by count and bytes."""
        out: list[LogEntry] = []
        size = 0
        i = from_index
        while i <= self._last_index and len(out) < max_count:
            e = self.get_entry(i)
            if e is None:
                break
            size += len(e.data)
            if out and size > max_bytes:
                break
            out.append(e)
            i += 1
        return out

    # -- appends ------------------------------------------------------------

    def stage_leader_entries(self, entries: list[LogEntry], term: int) -> LogId:
        """Leader: assign indexes/terms, make entries visible to replicators
        (in-memory) — synchronous, call under the node lock.  Durability
        comes from a following :meth:`flush_staged`."""
        for e in entries:
            self._last_index += 1
            e.id = LogId(self._last_index, term)
            self._mem_put(e)
            if e.type == EntryType.CONFIGURATION:
                self._track_conf(e)
        self._staged.extend(entries)
        self._wake_waiters()
        return LogId(self._last_index, term)

    async def flush_staged(self, upto: Optional[int] = None) -> None:
        """Flush all staged entries; resolves once the log is stable up to
        ``upto`` (default: everything staged so far).  Safe to call from
        multiple appliers concurrently — whoever runs first carries the
        whole staged batch; the rest wait on the stable watermark."""
        batch, self._staged = self._staged, []
        # default target: the full staged watermark (_last_index), NOT the
        # stable index — if another applier stole our staged batch we must
        # still wait for our entries' fsync before self-granting a vote
        target = upto if upto is not None else self._last_index
        if batch:
            await self._enqueue_flush(batch)
        if self._stable_index >= target:
            return
        fut = asyncio.get_running_loop().create_future()
        self._stable_waiters.append((target, fut))
        await fut

    async def append_entries_leader(self, entries: list[LogEntry], term: int
                                    ) -> LogId:
        """stage + flush in one call (single-applier convenience)."""
        last_id = self.stage_leader_entries(entries, term)
        await self.flush_staged(last_id.index)
        return last_id

    async def append_entries_follower(self, prev_log_index: int, prev_log_term: int,
                                      entries: list[LogEntry]) -> bool:
        """Conflict-checked follower append (#checkAndResolveConflict).

        Returns False when prev_log does not match (leader must back off).
        """
        if prev_log_index > self._last_index:
            return False  # gap: we don't have prev yet
        if prev_log_index >= self._first_index or (
            prev_log_index == self._last_snapshot_id.index
        ):
            if self.get_term(prev_log_index) != prev_log_term:
                return False
        # else: prev lies in the compacted region (its term is unknowable
        # unless it is the snapshot index) — those entries were committed,
        # so Raft's Log Matching property guarantees agreement.
        if not entries:
            return True
        # skip entries we already have with matching terms
        keep_from = 0
        for i, e in enumerate(entries):
            if (e.id.index < self._first_index
                    or e.id.index <= self._last_snapshot_id.index):
                # already compacted => committed; a stale retransmission
                keep_from = i + 1
                continue
            if e.id.index > self._last_index:
                keep_from = i
                break
            if self.get_term(e.id.index) != e.id.term:
                # conflict: truncate our suffix from this index
                if e.id.index <= self._applied_index:
                    raise RaftException(Status.error(
                        RaftError.EINTERNAL,
                        f"conflict at applied index {e.id.index}"))
                await self._truncate_suffix(e.id.index - 1)
                keep_from = i
                break
            keep_from = i + 1
        new_entries = entries[keep_from:]
        if not new_entries:
            return True
        # Deferred wire-CRC check, once per entry actually staged (the
        # wire decode skips it for speed): a blob corrupted past TCP's
        # 16-bit checksum must NOT reach the journal — recovery scans
        # would later mistake it for a torn tail and silently truncate
        # acked suffix entries.  Rejecting here makes the leader back
        # off and retransmit, turning corruption into a transient.
        try:
            for e in new_entries:
                e.verify_crc()
        except ValueError:
            LOG.warning("rejecting AppendEntries batch: wire CRC mismatch "
                        "at index %d", e.id.index)
            return False
        for e in new_entries:
            self._mem_put(e)
            self._last_index = e.id.index
            if e.type == EntryType.CONFIGURATION:
                self._track_conf(e)
        await self._enqueue_flush(new_entries)
        self._wake_waiters()
        return True

    def _track_conf(self, e: LogEntry) -> None:
        from tpuraft.conf import Configuration

        ce = ConfigurationEntry(
            id=e.id,
            conf=Configuration(list(e.peers or []), list(e.learners or []),
                               list(e.witnesses or [])),
            old_conf=Configuration(list(e.old_peers or []),
                                   list(e.old_learners or []),
                                   list(e.old_witnesses or [])),
        )
        self.conf_manager.add(ce)

    # -- flush pipeline ------------------------------------------------------

    async def _enqueue_flush(self, entries: list[LogEntry]) -> None:
        fut = asyncio.get_running_loop().create_future()
        self._inflight_flushes += 1
        self._flush_idle.clear()
        try:
            self._queue.append(_FlushReq(entries, fut))
            if self._flusher is None or self._flusher.done():
                self._flusher = asyncio.ensure_future(self._flush_loop())
            await fut
        finally:
            self._inflight_flushes -= 1
            if self._inflight_flushes == 0:
                self._flush_idle.set()

    async def _flush_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while self._queue:
            batch = [self._queue.popleft()]
            # coalesce everything already queued (AppendBatcher)
            while self._queue and len(batch) < self._max_flush_batch:
                batch.append(self._queue.popleft())
            entries = [e for r in batch for e in r.entries]
            try:
                if entries:
                    # shared-engine storages expose an async hook whose
                    # fsync joins a cross-GROUP commit round (multilog);
                    # classic storages block an executor thread
                    append_async = getattr(
                        self._storage, "append_entries_async", None)
                    health = self._health
                    # trace plane: spans for the traced entries of this
                    # flush round — timed IN the executor thread (the
                    # PR 11 health-probe discipline: awaited duration
                    # folds in executor-queue wait and a co-hosted
                    # neighbor's slow disk would contaminate THIS
                    # store's attribution exactly like it did the EMA)
                    tids = ([e.trace_id for e in entries if e.trace_id]
                            if _TRACE.enabled else [])
                    tok = health.disk.begin() if health is not None else None
                    try:
                        if append_async is not None:
                            # multilog: the group commit times its fsync
                            # IN the executor thread and feeds the EMA
                            # itself (StoreEngine wires the probe);
                            # begin/end here covers only the stall age.
                            # The awaited envelope is the best span
                            # available here (the commit round is
                            # shared, not per-group).
                            f0 = time.perf_counter()
                            await append_async(entries, self._sync)
                            f1 = time.perf_counter()
                        elif health is not None or tids:
                            # time the append+fsync IN the executor
                            # thread: end-to-end (awaited) duration
                            # would fold in executor-queue wait, and a
                            # co-hosted neighbor's slow disk must not
                            # score THIS store's disk sick
                            def _timed(entries=entries):
                                t0 = time.perf_counter()
                                self._storage.append_entries(entries,
                                                             self._sync)
                                return t0, time.perf_counter()

                            f0, f1 = await loop.run_in_executor(None, _timed)
                            if health is not None:
                                health.disk.note(f1 - f0)
                        else:
                            await loop.run_in_executor(
                                None, self._storage.append_entries, entries,
                                self._sync)
                    finally:
                        if tok is not None:
                            health.disk.end(tok)
                    if tids:
                        for tid in tids:
                            _TRACE.span(tid, "log_flush", f0, f1,
                                        proc=self._trace_proc,
                                        entries=len(entries))
                    self._stable_index = max(self._stable_index, entries[-1].id.index)
                    if self._disk_budget is not None:
                        # ~32B/entry framing+index overhead on top of
                        # payload — an estimate; the periodic reconcile
                        # re-bases on real usage
                        self._disk_budget.note_append(
                            sum(len(e.data) for e in entries)
                            + 32 * len(entries))
                    if self.on_stable is not None:
                        self.on_stable(self._stable_index)
                for r in batch:
                    if not r.future.done():
                        r.future.set_result(True)
                self._wake_stable_waiters()
            except Exception as exc:
                # storage failure is fatal for the LEADERSHIP, not the
                # process: every waiter gets a retryable error and the
                # on_storage_error hook steps the node down — never ack,
                # never silently drop (ISSUE 17 layer 4)
                LOG.exception("log flush failed")
                if self._disk_budget is not None and _is_enospc(exc):
                    self._disk_budget.note_enospc()
                err = RaftException(Status.error(RaftError.EIO, str(exc)))
                # Fail EVERYTHING in flight — this batch, every queued
                # request, the staged-but-unflushed tail — then roll the
                # in-memory frontier back to what storage actually
                # holds.  None of the failed suffix was ever acked, so
                # dropping it is the follower-conflict-truncate case,
                # not data loss; KEEPING it permanently desyncs memory
                # from disk — the next append dies "non-contiguous" in
                # storage and the node wedges in ERROR state (found by
                # the --disk-pressure soak's ENOSPC bursts).
                while self._queue:
                    batch.append(self._queue.popleft())
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(err)
                self._staged.clear()
                durable = max(self._storage.last_log_index(),
                              self._first_index - 1)
                for i in range(durable + 1, self._last_index + 1):
                    self._mem_pop(i)
                if durable < self._last_index:
                    self.conf_manager.truncate_suffix(durable)
                self._last_index = durable
                self._stable_index = min(self._stable_index, durable)
                for _, fut in self._stable_waiters:
                    if not fut.done():
                        fut.set_exception(err)
                self._stable_waiters.clear()
                cb = self.on_storage_error
                if cb is not None:
                    try:
                        cb(exc)
                    except Exception:
                        LOG.exception("on_storage_error hook failed")

    def _wake_stable_waiters(self) -> None:
        rest = []
        for target, fut in self._stable_waiters:
            if fut.done():
                continue
            if self._stable_index >= target:
                fut.set_result(None)
            else:
                rest.append((target, fut))
        self._stable_waiters = rest

    async def _drain_flushes(self) -> None:
        """Wait until every in-flight flush completed (before truncation —
        the reference funnels truncates through the same disruptor for the
        same ordering guarantee)."""
        await self._flush_idle.wait()

    async def _truncate_suffix(self, last_index_kept: int) -> None:
        await self._drain_flushes()
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._storage.truncate_suffix, last_index_kept)
        for i in range(last_index_kept + 1, self._last_index + 1):
            self._mem_pop(i)
        self._trim_floor = min(self._trim_floor, last_index_kept)
        self._last_index = last_index_kept
        self._stable_index = min(self._stable_index, last_index_kept)
        self.conf_manager.truncate_suffix(last_index_kept)
        if self.on_stable is not None:
            # the durable tip MOVED DOWN: replica-plane rows must follow
            # (a stale-high row would count truncated entries toward a
            # quorum — the divergent-suffix hazard)
            self.on_stable(self._stable_index)

    # -- snapshot interaction ------------------------------------------------

    async def set_snapshot(self, snapshot_id: LogId, conf: ConfigurationEntry,
                           keep_margin: int = 0) -> None:
        """Record a completed snapshot and compact the log prefix
        (reference: LogManagerImpl#setSnapshot + truncatePrefix)."""
        if snapshot_id.index <= self._last_snapshot_id.index:
            return
        term_here = self.get_term(snapshot_id.index)  # before updating snapshot id
        self._last_snapshot_id = snapshot_id
        self.conf_manager.set_snapshot(conf)
        first_kept = snapshot_id.index + 1 - keep_margin
        if term_here == snapshot_id.term:
            # local log agrees with the snapshot: keep the tail after it
            first_kept = min(first_kept, snapshot_id.index + 1)
        elif (term_here == 0 and self._first_index == snapshot_id.index + 1
                and self._last_index >= snapshot_id.index):
            # Boot-after-compaction: the entry AT the snapshot index was
            # already pruned (margin 0), so its term is unknowable — but
            # the stored tail starts exactly at snapshot.index + 1, i.e.
            # it was appended contiguously after the snapshot point and
            # Log Matching vouches for it.  KEEP it.  Treating term 0 as
            # divergence here reset the log and silently dropped the
            # whole acked suffix on every reboot that followed a
            # completed compaction — two such amnesiac reboots in one
            # fault window break quorum intersection and un-commit acked
            # writes (found by the power-loss soak, examples/soak.py
            # --power-loss; regression: tests/test_storage_fault.py).
            # The reference resets only on a KNOWN different term
            # (LogManagerImpl#setSnapshot: term == 0 -> truncatePrefix).
            return
        else:
            # log diverges from (or predates) the snapshot: drop everything
            await self._drain_flushes()
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, self._storage.reset, snapshot_id.index + 1)
            self._mem.clear()
            self._mem_bytes = 0
            self._trim_floor = snapshot_id.index
            self._first_index = snapshot_id.index + 1
            self._last_index = snapshot_id.index
            self._stable_index = snapshot_id.index
            self.conf_manager.truncate_prefix(self._first_index)
            if self.on_stable is not None:
                self.on_stable(self._stable_index)  # tip moved (reset)
            return
        first_kept = max(self._first_index, first_kept)
        if first_kept > self._first_index:
            await self._drain_flushes()
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._storage.truncate_prefix, first_kept)
            for i in range(self._first_index, first_kept):
                self._mem_pop(i)
            self._trim_floor = max(self._trim_floor, first_kept - 1)
            self._first_index = first_kept
            self.conf_manager.truncate_prefix(first_kept)

    def set_applied_index(self, index: int) -> None:
        self._applied_index = max(self._applied_index, index)
        # trim the in-memory window: stable AND applied entries can be
        # dropped, but keep a recent window (bounded by count AND bytes)
        # so replication reads stay off disk in the steady state.
        # Incremental: walk from the trim floor, never rescan _mem.
        trim_to = min(self._applied_index, self._stable_index,
                      self._last_index - self._max_in_memory)
        for i in range(self._trim_floor + 1, trim_to + 1):
            self._mem_pop(i)
        self._trim_floor = max(self._trim_floor, trim_to)
        # bytes cap: evict more of the oldest retained entries while
        # over budget, but never unstable or unapplied ones
        hard_to = min(self._applied_index, self._stable_index)
        i = self._trim_floor + 1
        while self._mem_bytes > self._max_in_memory_bytes and i <= hard_to:
            self._mem_pop(i)
            i += 1
        self._trim_floor = max(self._trim_floor, i - 1)

    # -- waiters (replicator wakeup) -----------------------------------------

    def wait_for(self, index: int) -> asyncio.Future:
        """Future resolving True when last_log_index >= index (or False on
        shutdown). Reference: LogManager#wait + wakeupAllWaiter."""
        fut = asyncio.get_running_loop().create_future()
        if self._last_index >= index or self._stopped:
            fut.set_result(self._last_index >= index)
            return fut
        self._waiters.append((index, fut))
        return fut

    def _wake_waiters(self, error: bool = False) -> None:
        rest: list[tuple[int, asyncio.Future]] = []
        for idx, fut in self._waiters:
            if fut.done():
                continue
            if error:
                fut.set_result(False)
            elif self._last_index >= idx:
                fut.set_result(True)
            else:
                rest.append((idx, fut))
        self._waiters = rest

    # -- consistency ---------------------------------------------------------

    def check_consistency(self) -> Status:
        if self._first_index == 1 and self._last_snapshot_id.index == 0:
            return Status.OK()
        if (self._last_snapshot_id.index >= self._first_index - 1
                and self._last_snapshot_id.index <= self._last_index):
            return Status.OK()
        if self._last_snapshot_id.index == self._last_index:
            return Status.OK()
        return Status.error(
            RaftError.EINTERNAL,
            f"inconsistent log: first={self._first_index} last={self._last_index} "
            f"snapshot={self._last_snapshot_id.index}",
        )
