"""Durable {term, votedFor} — the tiny file raft must fsync before voting.

Reference parity: ``core:storage/impl/LocalRaftMetaStorage`` over
``core:storage/io/ProtoBufFile`` (SURVEY.md §3.1).  Format: fixed little-
endian struct + crc32, written tmp-then-atomic-rename.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

from tpuraft.entity import EMPTY_PEER, PeerId

_FMT = struct.Struct("<qI")  # term, crc of (term||votedFor str)

# Per-target-path write serialization.  A store restart creates a NEW
# RaftMetaStorage over the SAME directory while the old node's last
# save may still be in flight on an executor thread: unserialized, the
# two saves raced on the shared .tmp name (os.replace ->
# FileNotFoundError aborting the voter's RPC handler) and, worse, the
# stale instance's save could land LAST and regress the durable term —
# letting the node double-vote after the next crash.  The regression
# guard reads the CURRENT file under the lock (disk is ground truth:
# the crash-consistency harness legitimately rolls the directory back
# to a durable-only image, which an in-memory registry would fight).
_paths_guard = threading.Lock()
_path_locks: dict[str, threading.Lock] = {}  # guarded-by: _paths_guard


def _path_lock(path: str) -> threading.Lock:
    with _paths_guard:
        lock = _path_locks.get(path)
        if lock is None:
            lock = _path_locks[path] = threading.Lock()
        return lock


class RaftMetaStorage:
    def __init__(self, dir_path: str, sync: bool = True):
        self._dir = dir_path
        self._sync = sync
        self.term = 0
        self.voted_for: PeerId = EMPTY_PEER

    def _path(self) -> str:
        return os.path.join(self._dir, "raft_meta")

    def init(self) -> None:
        os.makedirs(self._dir, exist_ok=True)
        try:
            with open(self._path(), "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return
        if len(blob) < _FMT.size:
            raise IOError(f"raft meta truncated in {self._dir}")
        term, crc = _FMT.unpack_from(blob, 0)
        voted = blob[_FMT.size:]
        if zlib.crc32(struct.pack("<q", term) + voted) != crc:
            raise IOError(f"raft meta corrupted in {self._dir}")
        self.term = term
        self.voted_for = PeerId.parse(voted.decode()) if voted else EMPTY_PEER

    def set_term_and_voted_for(self, term: int, voted_for: PeerId) -> None:
        self.term = term
        self.voted_for = voted_for
        # pass the values explicitly: _save may run on an executor thread
        # while the event loop rebinds the mirror fields for a NEWER save
        # — re-reading self.term there could persist a torn pair
        self._save(term, voted_for)

    def set_term(self, term: int) -> None:
        self.set_term_and_voted_for(term, self.voted_for)

    def set_voted_for(self, voted_for: PeerId) -> None:
        self.set_term_and_voted_for(self.term, voted_for)

    @staticmethod
    def _read_durable(path: str) -> tuple[int, str]:
        """Best-effort read of the currently persisted {term, votedFor}
        — (-1, "") when missing/corrupt (a fresh write then proceeds)."""
        try:
            with open(path, "rb") as f:
                blob = f.read()
            term, crc = _FMT.unpack_from(blob, 0)
            voted = blob[_FMT.size:]
            if zlib.crc32(struct.pack("<q", term) + voted) != crc:
                return -1, ""
            return term, voted.decode()
        except (OSError, struct.error, UnicodeDecodeError):
            return -1, ""

    def _save(self, term: int, voted_for: PeerId) -> None:
        voted_s = "" if voted_for.is_empty() else str(voted_for)
        voted = voted_s.encode()
        path = self._path()
        with _path_lock(os.path.abspath(path)):
            cur_term, cur_voted = self._read_durable(path)
            if term < cur_term:
                return  # stale instance's late save: never regress term
            if term == cur_term and cur_voted and voted_s != cur_voted:
                # within one term a persisted vote must never be
                # forgotten or switched (double-vote after a crash)
                return
            crc = zlib.crc32(struct.pack("<q", term) + voted)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_FMT.pack(term, crc) + voted)
                f.flush()
                if self._sync:
                    os.fsync(f.fileno())
            os.replace(tmp, path)
            if self._sync:
                fd = os.open(self._dir, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)

    def shutdown(self) -> None:
        pass


class MemoryRaftMetaStorage(RaftMetaStorage):
    """Volatile variant for tests/benchmarks."""

    # _save is a no-op: callers may persist {term, votedFor} inline on
    # the event loop (Node._persist_meta fast path, send-plane inline
    # vote-response handling) instead of paying an executor round
    SYNC_CHEAP = True

    def __init__(self) -> None:
        super().__init__("", sync=False)

    def init(self) -> None:
        pass

    def _save(self, term: int, voted_for: PeerId) -> None:
        pass
