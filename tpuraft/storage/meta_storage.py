"""Durable {term, votedFor} — the tiny file raft must fsync before voting.

Reference parity: ``core:storage/impl/LocalRaftMetaStorage`` over
``core:storage/io/ProtoBufFile`` (SURVEY.md §3.1).  Format: fixed little-
endian struct + crc32, written tmp-then-atomic-rename.
"""

from __future__ import annotations

import os
import struct
import zlib

from tpuraft.entity import EMPTY_PEER, PeerId

_FMT = struct.Struct("<qI")  # term, crc of (term||votedFor str)


class RaftMetaStorage:
    def __init__(self, dir_path: str, sync: bool = True):
        self._dir = dir_path
        self._sync = sync
        self.term = 0
        self.voted_for: PeerId = EMPTY_PEER

    def _path(self) -> str:
        return os.path.join(self._dir, "raft_meta")

    def init(self) -> None:
        os.makedirs(self._dir, exist_ok=True)
        try:
            with open(self._path(), "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return
        if len(blob) < _FMT.size:
            raise IOError(f"raft meta truncated in {self._dir}")
        term, crc = _FMT.unpack_from(blob, 0)
        voted = blob[_FMT.size:]
        if zlib.crc32(struct.pack("<q", term) + voted) != crc:
            raise IOError(f"raft meta corrupted in {self._dir}")
        self.term = term
        self.voted_for = PeerId.parse(voted.decode()) if voted else EMPTY_PEER

    def set_term_and_voted_for(self, term: int, voted_for: PeerId) -> None:
        self.term = term
        self.voted_for = voted_for
        self._save()

    def set_term(self, term: int) -> None:
        self.set_term_and_voted_for(term, self.voted_for)

    def set_voted_for(self, voted_for: PeerId) -> None:
        self.set_term_and_voted_for(self.term, voted_for)

    def _save(self) -> None:
        voted = b"" if self.voted_for.is_empty() else str(self.voted_for).encode()
        crc = zlib.crc32(struct.pack("<q", self.term) + voted)
        tmp = self._path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_FMT.pack(self.term, crc) + voted)
            f.flush()
            if self._sync:
                os.fsync(f.fileno())
        os.replace(tmp, self._path())
        if self._sync:
            fd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)

    def shutdown(self) -> None:
        pass


class MemoryRaftMetaStorage(RaftMetaStorage):
    """Volatile variant for tests/benchmarks."""

    # _save is a no-op: callers may persist {term, votedFor} inline on
    # the event loop (Node._persist_meta fast path, send-plane inline
    # vote-response handling) instead of paying an executor round
    SYNC_CHEAP = True

    def __init__(self) -> None:
        super().__init__("", sync=False)

    def init(self) -> None:
        pass

    def _save(self) -> None:
        pass
