"""Shared multi-group {term, votedFor} journal with group-commit fsync.

Reference parity: ``core:storage/impl/LocalRaftMetaStorage`` semantics —
{term, votedFor} is fsynced before a vote is cast or a term adopted —
at multi-raft density (SURVEY.md §3.1 "synced on change", §3.5 cluster
sharding).  The reference pays one ProtoBufFile fsync per group per
change; a 16K-group election herd on one process would issue 16K fsyncs
serially through the executor, which is exactly the r3 starvation
regime.  Here every group of a process appends its meta record to ONE
shared journal whose flushes coalesce through the same group-commit
*machinery* the multilog uses
(:class:`tpuraft.storage.multilog._GroupCommit`) — but over its own
file and its own rounds, so meta saves coalesce with other meta saves,
not with log-entry fsyncs (an election plus an append burst pays two
fsync rounds, one per journal): N groups voting concurrently still cost
one meta fsync.

Wiring::

    raft_meta_uri = "multimeta://<dir>#<group_id>"

One :class:`MetaJournal` per directory per process (registry below);
each node's :class:`MultiRaftMetaStorage` is a per-group facade exposing
the synchronous ``RaftMetaStorage`` interface plus ``save_async`` —
``Node._persist_meta`` awaits that, so an election herd's meta persists
ride shared fsync rounds instead of serial executor hops.

On-disk format (``meta.jnl``): repeated
``[u16 glen | group | i64 term | u16 vlen | votedFor | u32 crc]``,
last record per group wins.  Durability watermark (``meta.jnl.synced``)
follows the FileLogStorage discipline: a scan failure BELOW the
watermark is loud corruption (an acked vote may be lost — restarting
blind could double-vote), at/above it is a truncatable torn tail (that
save was never acked).  The journal compacts in place (tmp + fsync +
rename) once garbage dominates.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from typing import Optional

from tpuraft.entity import EMPTY_PEER, PeerId
from tpuraft.storage.log_storage import (
    CorruptLogError,
    _fsync_dir,
    load_crc_watermark,
    save_crc_watermark,
)
from tpuraft.storage.meta_storage import RaftMetaStorage

_HDR = struct.Struct("<H")      # group / votedFor length prefixes
_TERM = struct.Struct("<q")
_CRC = struct.Struct("<I")

_JNL = "meta.jnl"
_WM = "meta.jnl.synced"

LOG = logging.getLogger(__name__)


def _record(group: bytes, term: int, voted: bytes) -> bytes:
    payload = _HDR.pack(len(group)) + group + _TERM.pack(term) \
        + _HDR.pack(len(voted)) + voted
    return payload + _CRC.pack(zlib.crc32(payload))


class MetaJournal:
    """One shared meta journal + group-commit (one per directory)."""

    # compact when the journal carries ~8x more records than live groups
    # (and is big enough for the rewrite to matter)
    COMPACT_MIN_BYTES = 256 * 1024

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        # guards the file handle, the value map and compaction: stagers
        # run on event loops, the fsync runs in executor threads
        self._lock = threading.Lock()
        # serializes whole fsync rounds with compaction's file-handle
        # swap and with close() (mirrors the native engine's sync_mu):
        # without it, a synchronous _save-path sync() racing a
        # group-commit round's compaction would fsync a closed handle —
        # ValueError remapped to a spurious IOError("meta journal
        # closed") failing every waiter in the batch.  Lock order:
        # _sync_lock -> _lock, never the reverse; stage() takes only
        # _lock so staging never stalls behind a flush.
        self._sync_lock = threading.Lock()
        self._values: dict[bytes, tuple[int, bytes]] = {}  # guarded-by: _lock
        self._f = None      # guarded-by: _lock
        self._size = 0      # guarded-by: _lock
        # bytes proven durable by a completed fsync
        self._synced = 0    # guarded-by: _lock
        self._refs = 0
        self.sync_count = 0
        self.save_count = 0
        self._open()
        from tpuraft.storage.multilog import _GroupCommit

        self.group_commit = _GroupCommit(self)

    # -- recovery ------------------------------------------------------------

    def _path(self) -> str:
        return os.path.join(self.dir, _JNL)

    def _wm_path(self) -> str:
        return os.path.join(self.dir, _WM)

    def _load_wm(self) -> int:
        # CRC-guarded (see load_crc_watermark): garbage degrades to 0 =
        # nothing proven, which always falls back to torn-tail semantics
        vals = load_crc_watermark(self._wm_path(), 8)
        return struct.unpack("<q", vals)[0] if vals is not None else 0

    def _save_wm(self, sync: bool) -> None:  # graftcheck: holds(_lock)
        try:
            save_crc_watermark(self._wm_path(), self.dir,
                               struct.pack("<q", self._synced), sync)
        except OSError:
            # same policy as FileLogStorage._save_watermark: the
            # sync=True save is the compaction FLOOR and must abort the
            # compaction on failure; the non-sync saves (open, close,
            # post-compaction refresh) only ADVANCE the watermark, and
            # stale-LOW always degrades to torn-tail scan semantics —
            # ENOSPC on the watermark tmp must not fail close/boot
            if sync:
                raise
            LOG.warning("meta watermark save failed (stale-LOW, "
                        "non-fatal)", exc_info=True)
            try:
                os.remove(self._wm_path() + ".tmp")
            except OSError:
                pass

    # graftcheck: allow(guarded-by) — construction-time: runs inside __init__, before the journal is shared
    def _open(self) -> None:
        wm = self._load_wm()
        exists = os.path.exists(self._path())
        self._f = open(self._path(), "r+b" if exists else "w+b")
        blob = self._f.read()
        off, good = 0, 0
        while off + _HDR.size <= len(blob):
            try:
                (glen,) = _HDR.unpack_from(blob, off)
                p = off + _HDR.size
                group = blob[p:p + glen]
                p += glen
                (term,) = _TERM.unpack_from(blob, p)
                p += _TERM.size
                (vlen,) = _HDR.unpack_from(blob, p)
                p += _HDR.size
                voted = blob[p:p + vlen]
                p += vlen
                (crc,) = _CRC.unpack_from(blob, p)
                p += _CRC.size
                if len(group) != glen or len(voted) != vlen \
                        or zlib.crc32(blob[off:p - _CRC.size]) != crc:
                    raise ValueError("bad record")
            except (struct.error, ValueError):
                if off < wm:
                    raise CorruptLogError(
                        f"{self._path()}: record at offset {off} inside "
                        f"the durable region (<{wm}) fails scan — an "
                        f"acked {{term, votedFor}} may be lost; refusing "
                        f"to truncate (double-vote hazard)")
                break  # torn tail: that save was never acked
            self._values[group] = (term, voted)
            off = p
            good = off
        if good < wm:
            raise CorruptLogError(
                f"{self._path()}: durable region ran to {wm} bytes but "
                f"only {good} scan clean — acked meta lost")
        if good < len(blob):
            self._f.truncate(good)
        self._size = good
        # surviving bytes may still be page-cache-dirty (crash-restart):
        # prove them before claiming them durable
        self._f.flush()
        os.fsync(self._f.fileno())
        self._synced = good
        self._save_wm(sync=False)

    # -- staging + group commit ----------------------------------------------

    def stage(self, group: str, term: int, voted: PeerId) -> None:
        g = group.encode()
        v = b"" if voted.is_empty() else str(voted).encode()
        rec = _record(g, term, v)
        with self._lock:
            if self._f is None:
                raise IOError("meta journal closed")
            self._f.seek(self._size)
            self._f.write(rec)
            self._size += len(rec)
            self._values[g] = (term, v)
            self.save_count += 1

    def sync(self) -> None:
        """One fsync round (called by _GroupCommit, possibly from an
        executor thread); compacts when garbage dominates.

        The fsync runs OUTSIDE the staging lock: stage() is called
        inline on the event loop (save_async), and holding that lock
        through a writeback-stalled fsync would stall the loop —
        heartbeats for every group in the process — exactly what the
        group-commit machinery exists to prevent.  ``_sync_lock`` is
        held for the whole round instead, so a concurrent round (the
        synchronous ``_save`` path racing a group-commit round) cannot
        interleave with compaction closing the handle mid-fsync.  Only
        bytes staged BEFORE this flush are claimed synced."""
        with self._sync_lock:
            with self._lock:
                if self._f is None:
                    raise IOError("meta journal closed")
                f = self._f
                f.flush()
                size = self._size
            try:
                os.fsync(f.fileno())
            except ValueError:
                # unreachable while _sync_lock serializes close() and
                # compaction; kept as a defensive remap
                raise IOError("meta journal closed")
            with self._lock:
                self.sync_count += 1
                if self._f is f and size > self._synced:
                    self._synced = size
                live = max(1, len(self._values))
                if (self._f is f and size >= self.COMPACT_MIN_BYTES
                        and self._size > 8 * live * 64):
                    # compaction stays under both locks (it swaps the
                    # file handle out from under stagers and fsyncers):
                    # rare — threshold-gated — and bounded by the live
                    # set's size, unlike the per-round fsync above
                    try:
                        self._compact_locked()
                    except OSError:
                        # compaction is an optimization: a rewrite that
                        # dies ENOSPC (tmp copy on a full disk) must not
                        # fail the sync round that already fsynced — the
                        # journal handle and staged bytes are untouched
                        # (os.replace either never ran or landed whole).
                        # Drop the partial tmp; a later round retries.
                        try:
                            os.remove(self._path() + ".tmp")
                        except OSError:
                            pass

    def _compact_locked(self) -> None:
        # floor the watermark (fsynced) BEFORE replacing the file: if the
        # rename lands and a higher watermark write doesn't, boot would
        # demand old-size bytes from the new, smaller file
        self._synced = 0
        self._save_wm(sync=True)
        tmp = self._path() + ".tmp"
        with open(tmp, "wb") as f:
            for g, (term, v) in self._values.items():
                f.write(_record(g, term, v))
            f.flush()
            os.fsync(f.fileno())
            new_size = f.tell()
        os.replace(tmp, self._path())
        _fsync_dir(self.dir)
        self._f.close()
        self._f = open(self._path(), "r+b")
        self._size = new_size
        self._synced = new_size
        self._save_wm(sync=False)  # stale-LOW safe

    # -- per-group access ----------------------------------------------------

    def get(self, group: str) -> tuple[int, PeerId]:
        with self._lock:
            term, v = self._values.get(group.encode(), (0, b""))
        return term, (PeerId.parse(v.decode()) if v else EMPTY_PEER)

    def close(self) -> None:
        # _sync_lock first: an in-flight sync round must finish its
        # fsync before the handle disappears (same discipline as
        # MultiLogEngine.close vs its sync lock)
        with self._sync_lock, self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                    self._synced = self._size
                    self._save_wm(sync=False)
                finally:
                    self._f.close()
                    self._f = None


# -- process-level registry (one journal per directory), like multilog -------

_journals_lock = threading.Lock()
_journals: dict[str, MetaJournal] = {}  # guarded-by: _journals_lock


def get_journal(dir_path: str) -> MetaJournal:
    key = os.path.realpath(dir_path)
    with _journals_lock:
        j = _journals.get(key)
        if j is None or j._f is None:
            j = MetaJournal(dir_path)
            _journals[key] = j
        j._refs += 1
        return j


def _release_journal(j: MetaJournal) -> None:
    key = os.path.realpath(j.dir)
    with _journals_lock:
        j._refs -= 1
        if j._refs > 0:
            return
        _journals.pop(key, None)
        # close INSIDE the registry lock: a concurrent get_journal on
        # the same directory must not reopen (and possibly truncate a
        # torn tail + lower the watermark) while this handle is still
        # flushing — the final flush here could otherwise re-persist a
        # higher watermark than the new handle's truncated size, a
        # false CorruptLogError at the next boot
        j.close()


class MultiRaftMetaStorage(RaftMetaStorage):
    """Per-group facade over the shared :class:`MetaJournal`.

    Implements the synchronous ``RaftMetaStorage`` interface (each save
    = stage + engine fsync) plus ``save_async`` — stage inline, then join
    the shared group-commit round so concurrent groups' meta persists
    cost one fsync.  ``Node._persist_meta`` prefers ``save_async``.
    """

    def __init__(self, dir_path: str, group: str):
        super().__init__(dir_path, sync=True)
        self._group = group
        self._jnl: Optional[MetaJournal] = None

    def init(self) -> None:
        self._jnl = get_journal(self._dir)
        self.term, self.voted_for = self._jnl.get(self._group)

    def _save(self, term: int, voted_for: PeerId) -> None:
        assert self._jnl is not None, "init() first"
        self._jnl.stage(self._group, term, voted_for)
        self._jnl.sync()

    async def save_async(self, term: int, voted_for: PeerId) -> None:
        assert self._jnl is not None, "init() first"
        self.term = term
        self.voted_for = voted_for
        self._jnl.stage(self._group, term, voted_for)
        await self._jnl.group_commit.flush()

    def shutdown(self) -> None:
        if self._jnl is not None:
            _release_journal(self._jnl)
            self._jnl = None
