"""Durable raft log storage.

Reference parity: ``core:storage/LogStorage`` interface and
``core:storage/impl/RocksDBLogStorage`` (SURVEY.md §3.1 "Log storage").
Where the reference keys RocksDB by 8-byte big-endian index, this build
uses a *segmented append log* purpose-built for raft's access pattern
(append-mostly, contiguous reads, prefix/suffix truncation) — the same
format the native C++ engine (native/logstore.cc) implements, selected by
``log_uri`` scheme:

  memory://            in-memory (tests, benchmarks)
  file://<dir>         Python segmented log (this module)
  native://<dir>       C++ engine via ctypes (tpuraft.storage.native_log)

On-disk format per segment ``seg_<first_index>.log``:
  repeated [ u32 frame_len | LogEntry.encode() bytes ]  (CRC inside entry)
A tiny ``meta`` file persists first_log_index for prefix truncation.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from abc import ABC, abstractmethod
from typing import Optional

from tpuraft.entity import EntryType, LogEntry

LOG = logging.getLogger(__name__)

_FRAME = struct.Struct("<I")
# durable_end sentinel: "this whole segment was complete at watermark time"
_DURABLE_ALL = 1 << 62


class CorruptLogError(Exception):
    """Mid-log corruption (valid entries beyond a bad frame).

    Distinct from a torn tail: truncating here would silently drop
    acked suffix entries, so startup fails loudly instead.
    """


def _fsync_dir(path: str) -> None:
    """fsync a directory so renames/creations inside it survive power loss."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_crc_watermark(path: str, dir_path: str, vals: bytes,
                       sync: bool) -> None:
    """Write a durability watermark as [values | crc32(values)] via
    tmp + atomic rename (fsynced only when ``sync``— stale-LOW is
    always safe, so the ordinary save skips the fsync)."""
    blob = vals + struct.pack("<I", zlib.crc32(vals))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        if sync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if sync:
        _fsync_dir(dir_path)


def load_crc_watermark(path: str, value_size: int) -> Optional[bytes]:
    """CRC-guarded watermark read: the ordinary save is NOT fsynced, so
    after a power loss the file can hold garbage — and a garbage
    watermark read as trusted would brick a healthy store with a false
    CorruptLogError.  Returns the raw value bytes ONLY for an exact
    [values | crc32(values)] record; anything else — absent, wrong
    size (pre-CRC legacy files and prefix-torn records included), or a
    CRC mismatch — returns None and the caller degrades to its
    nothing-proven sentinel, which always falls back to safe torn-tail
    semantics.  Trusting bare value_size-byte content was considered
    and rejected: partial-page writeback can leave right-sized GARBAGE
    (a torn CRC record with flipped bytes), and a garbage-high value
    bricks recovery; degrading a legacy watermark once costs only one
    boot's fail-loud coverage."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        return None
    if len(blob) != value_size + 4:
        return None
    (crc,) = struct.unpack_from("<I", blob, value_size)
    if zlib.crc32(blob[:value_size]) == crc:
        return blob[:value_size]
    return None


class LogStorage(ABC):
    """Synchronous storage SPI; LogManager wraps it with async batching."""

    @abstractmethod
    def init(self) -> None: ...

    @abstractmethod
    def shutdown(self) -> None: ...

    @abstractmethod
    def first_log_index(self) -> int: ...

    @abstractmethod
    def last_log_index(self) -> int: ...

    @abstractmethod
    def get_entry(self, index: int) -> Optional[LogEntry]: ...

    def get_term(self, index: int) -> int:
        e = self.get_entry(index)
        return e.id.term if e else 0

    @abstractmethod
    def append_entries(self, entries: list[LogEntry], sync: bool = True) -> int:
        """Append a batch; returns count appended."""

    @abstractmethod
    def truncate_prefix(self, first_index_kept: int) -> None:
        """Drop entries < first_index_kept (snapshot compaction)."""

    @abstractmethod
    def truncate_suffix(self, last_index_kept: int) -> None:
        """Drop entries > last_index_kept (conflict resolution)."""

    @abstractmethod
    def reset(self, next_log_index: int) -> None:
        """Drop everything; next append starts at next_log_index
        (InstallSnapshot beyond local log)."""

    def configuration_indexes(self) -> list[int]:
        """Indexes of CONFIGURATION entries currently stored — lets the
        LogManager rebuild configuration history without an O(n) scan
        (the reference keeps conf entries in their own column family)."""
        return [
            i
            for i in range(self.first_log_index(), self.last_log_index() + 1)
            if (e := self.get_entry(i)) and e.type == EntryType.CONFIGURATION
        ]


class MemoryLogStorage(LogStorage):
    """Reference test double (``MemoryLogStorage`` exists upstream too)."""

    CHEAP_CONF_INDEXES = True  # dict walk, no disk

    def __init__(self) -> None:
        self._entries: dict[int, LogEntry] = {}
        self._first = 1
        self._last = 0

    def init(self) -> None:
        pass

    def shutdown(self) -> None:
        self._entries.clear()

    def first_log_index(self) -> int:
        return self._first

    def last_log_index(self) -> int:
        return self._last

    def get_entry(self, index: int) -> Optional[LogEntry]:
        return self._entries.get(index)

    def append_entries(self, entries: list[LogEntry], sync: bool = True) -> int:
        for e in entries:
            self._entries[e.id.index] = e
            self._last = max(self._last, e.id.index)
        return len(entries)

    def truncate_prefix(self, first_index_kept: int) -> None:
        for i in range(self._first, first_index_kept):
            self._entries.pop(i, None)
        self._first = max(self._first, first_index_kept)
        if self._last < self._first - 1:
            self._last = self._first - 1

    def truncate_suffix(self, last_index_kept: int) -> None:
        for i in range(last_index_kept + 1, self._last + 1):
            self._entries.pop(i, None)
        self._last = min(self._last, last_index_kept)

    def reset(self, next_log_index: int) -> None:
        self._entries.clear()
        self._first = next_log_index
        self._last = next_log_index - 1


class _Segment:
    """One append-only segment file with an in-memory offset index."""

    def __init__(self, path: str, first_index: int):
        self.path = path
        self.first_index = first_index
        self.offsets: list[int] = []  # offsets[i] = file offset of entry first_index+i
        self.size = 0
        self._f = None  # type: ignore[assignment]

    @property
    def last_index(self) -> int:
        return self.first_index + len(self.offsets) - 1

    def open(self, durable_end: int = 0) -> None:
        exists = os.path.exists(self.path)
        self._f = open(self.path, "r+b" if exists else "w+b")
        if exists:
            self._scan(durable_end)

    def _scan(self, durable_end: int) -> None:
        """Rebuild the offset index; truncate a torn tail write if found.

        ``durable_end``: bytes below it were verified present at an
        earlier startup (the store's ``synced`` watermark).  A failure
        BELOW it can't be a torn in-flight write — it is corruption of
        previously-durable (acked, possibly committed) data, and
        truncating there would silently drop the log suffix, so fail
        loudly and let the operator rebuild the replica from a
        snapshot.  At/above the watermark nothing was acked against a
        completed fsync, and unordered page writeback can legitimately
        persist a LATER entry's blocks while losing an earlier one's —
        so any failure there is a truncatable torn tail, valid-looking
        bytes after it notwithstanding.
        """
        f = self._f
        f.seek(0, os.SEEK_END)
        end = f.tell()
        f.seek(0)
        off = 0
        good_end = 0
        while off + _FRAME.size <= end:
            f.seek(off)
            (flen,) = _FRAME.unpack(f.read(_FRAME.size))
            if off + _FRAME.size + flen > end:
                if off < durable_end:
                    raise CorruptLogError(
                        f"{self.path}: frame at offset {off} overruns the "
                        f"file inside the durable region (<{durable_end}) — "
                        f"refusing to truncate acked suffix")
                break  # torn write
            blob = f.read(flen)
            try:
                LogEntry.decode(blob)  # CRC + framing check
            except (ValueError, struct.error):
                if off < durable_end:
                    raise CorruptLogError(
                        f"{self.path}: CRC/framing failure at offset {off} "
                        f"inside the durable region (<{durable_end}) — "
                        f"refusing to truncate acked suffix")
                break
            self.offsets.append(off)
            off += _FRAME.size + flen
            good_end = off
        if durable_end >= _DURABLE_ALL:
            # fully-durable segment (strictly below the watermark
            # segment): its exact size wasn't recorded, so the most we
            # can demand is that every byte present scans clean
            bad = good_end < end
        else:
            # watermark segment: at least the recorded size must scan
            # clean — catches clean-at-a-frame-boundary shrinkage too
            # (no bad frame to trip on, the file just ends early)
            bad = good_end < durable_end
        if bad:
            raise CorruptLogError(
                f"{self.path}: durable region ran to "
                f"{min(durable_end, end)} bytes but only {good_end} scan "
                f"clean — acked entries lost")
        if good_end < end:
            f.truncate(good_end)
        self.size = good_end

    def append(self, blob: bytes) -> None:
        self._f.seek(self.size)
        self._f.write(_FRAME.pack(len(blob)))
        self._f.write(blob)
        self.offsets.append(self.size)
        self.size += _FRAME.size + len(blob)

    def read(self, index: int) -> LogEntry:
        off = self.offsets[index - self.first_index]
        self._f.seek(off)
        (flen,) = _FRAME.unpack(self._f.read(_FRAME.size))
        return LogEntry.decode(self._f.read(flen))

    def truncate_to(self, last_index_kept: int) -> None:
        n_keep = last_index_kept - self.first_index + 1
        if n_keep >= len(self.offsets):
            return
        new_size = self.offsets[n_keep] if n_keep > 0 else 0
        self._f.truncate(new_size)
        self._f.flush()
        os.fsync(self._f.fileno())
        del self.offsets[n_keep:]
        self.size = new_size

    def sync(self) -> None:
        f = self._f
        if f is None:
            return  # deleted/closed concurrently; its data is gone anyway
        try:
            f.flush()
            os.fsync(f.fileno())
        except ValueError:
            pass  # closed between the check and the flush

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def delete(self) -> None:
        self.close()
        if os.path.exists(self.path):
            os.remove(self.path)


class FileLogStorage(LogStorage):
    """Segmented append-log storage (Python implementation)."""

    SEGMENT_MAX_BYTES = 64 * 1024 * 1024

    def __init__(self, dir_path: str, segment_max_bytes: int | None = None):
        self._dir = dir_path
        self._segments: list[_Segment] = []     # guarded-by: _lock
        self._first = 1                         # guarded-by: _lock
        self._seg_max = segment_max_bytes or self.SEGMENT_MAX_BYTES
        self._conf_indexes: list[int] = []      # guarded-by: _lock
        # synced frontier (active_segment_first_index, size): the bytes
        # PROVEN on disk by a completed fsync.  The persisted watermark
        # (`synced` file) only ever records this value, so it can never
        # run ahead of durability (stale-HIGH), which would turn a
        # legitimate torn tail into a false CorruptLogError.
        self._synced = (-1, 0)                  # guarded-by: _lock
        # guards _segments and file handles: the event loop reads (get_entry)
        # while the LogManager flusher appends/truncates in executor threads
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    # graftcheck: allow(guarded-by) — init-time: the LogManager flusher that shares these fields does not exist yet
    def init(self) -> None:
        os.makedirs(self._dir, exist_ok=True)
        self._load_meta()
        wm_first, wm_size = self._load_watermark()
        names = sorted(
            (n for n in os.listdir(self._dir) if n.startswith("seg_") and n.endswith(".log")),
            key=lambda n: int(n[4:-4]),
        )
        on_disk_firsts = [int(n[4:-4]) for n in names]
        # Segments provably compacted garbage from names alone: a
        # below-first segment ends strictly before the next on-disk
        # segment's first index, so ANY on-disk successor starting in
        # (its_first, first_log_index] proves its whole range is below
        # first_log_index — leftovers of a crash mid truncate_prefix.
        # Scan those tolerantly (durable_end=0): demanding durable-
        # region cleanliness of about-to-be-deleted garbage would brick
        # boot on a torn (or rotted) tail that never mattered.
        stale_certain = {
            fi for fi in on_disk_firsts if fi < self._first and any(
                fi < nx <= self._first for nx in on_disk_firsts)}
        wm_seg_verified_stale = False
        drop_rest = False
        for n in names:
            first_index = int(n[4:-4])
            # durable region (see _Segment._scan): segments strictly
            # below the watermark segment were complete when the
            # watermark was recorded; the watermark segment is durable
            # up to the recorded size; later segments not at all
            if first_index in stale_certain:
                durable_end = 0
            elif first_index < wm_first:
                durable_end = _DURABLE_ALL
            elif first_index == wm_first:
                durable_end = wm_size
            else:
                durable_end = 0
            seg = _Segment(os.path.join(self._dir, n), first_index)
            seg.open(durable_end)
            # stale: fully below first_log_index — crash mid truncate_prefix
            # (meta saved, file not yet deleted)
            stale = seg.first_index < self._first and (
                not seg.offsets or seg.last_index < self._first
            )
            if drop_rest or stale:
                if stale and first_index == wm_first \
                        and first_index not in stale_certain:
                    # the watermark segment scanned clean at its recorded
                    # durable size and is entirely below first_log_index:
                    # provably compacted, nothing acked lost
                    wm_seg_verified_stale = True
                seg.delete()
                continue
            if not seg.offsets or (
                self._segments
                and seg.first_index != self._segments[-1].last_index + 1
            ):
                # empty (torn) segment or a hole from a torn multi-segment
                # batch append: everything from here on is unreachable.
                # But a hole or vanished bytes in the DURABLE region is
                # the fail-loud case — deleting would silently drop the
                # acked suffix just like a silent truncation would.
                expected = (self._segments[-1].last_index + 1
                            if self._segments else self._first)
                if durable_end > 0 or expected < wm_first:
                    raise CorruptLogError(
                        f"{self._dir}: durable segment(s) missing or empty "
                        f"around index {expected} (watermark segment "
                        f"{wm_first}) — refusing to drop acked suffix")
                seg.delete()
                drop_rest = True
                continue
            self._segments.append(seg)
        if wm_size > 0 and not any(s.first_index == wm_first
                                   for s in self._segments):
            # The watermark segment itself vanished with recorded bytes
            # in it.  One legitimate cause: prefix compaction deleted it
            # (truncate_prefix only removes segments ENTIRELY below
            # first_log_index, and a crash between _save_meta and the
            # segment deletes leaves the same state via init's stale
            # cleanup above).  That case is provable from what WAS on
            # disk at boot: some segment started in (wm_first, _first],
            # so the watermark segment ended strictly below _first —
            # every index it held is compacted, nothing acked is lost.
            # Anything else (segment straddling _first gone, or no
            # bounding successor) is external loss: fail loudly.
            compacted = wm_seg_verified_stale or (
                wm_first < self._first and any(
                    wm_first < fi <= self._first for fi in on_disk_firsts))
            if not compacted:
                raise CorruptLogError(
                    f"{self._dir}: watermark segment seg_{wm_first}.log "
                    f"({wm_size} durable bytes) is missing — acked entries "
                    f"lost")
        self._load_conf_indexes()
        # Bytes at/above the loaded watermark are readable but possibly
        # still dirty in the page cache (crash-restart case): fsync them
        # before advancing the watermark over them, or a power loss in
        # the writeback window would turn the watermark into a false
        # corruption alarm at the NEXT boot.  Bytes below it were
        # fsynced before that watermark was recorded — skip (O(1)
        # fsyncs at boot, not O(#segments)).
        for seg in self._segments:
            if seg.first_index >= wm_first:
                seg.sync()
        if self._segments:
            last = self._segments[-1]
            self._synced = (last.first_index, last.size)
        else:
            self._synced = (-1, 0)
        self._save_watermark()

    def shutdown(self) -> None:
        # clean shutdown: fsync + advance the watermark over everything
        # written this run, so the next scan treats it all as durable.
        # Everything at/above the synced frontier may be dirty (rolled
        # segments in a sync=False run included) — flush it all.
        # Under _lock: a snapshot compaction still running in an
        # executor thread (truncate_prefix) mutates _segments, and the
        # unguarded walk raced it into an IndexError mid-shutdown.
        with self._lock:
            if self._segments:
                for s in self._segments:
                    if s.first_index >= self._synced[0]:
                        s.sync()
                last = self._segments[-1]
                self._synced = (last.first_index, last.size)
                self._save_watermark()
            for s in self._segments:
                s.close()
            self._segments.clear()

    # -- durability watermark ------------------------------------------------
    # Persists the synced frontier (active_segment_first_index, size) —
    # recorded at init (after scan + fsync), clean shutdown, and around
    # destructive ops; never on the append hot path.  Stale-LOW is
    # always safe (falls back to torn-tail truncation semantics), so the
    # ordinary save is not fsynced.  Destructive ops (suffix truncation,
    # reset) FIRST persist a lowered floor WITH fsync: the reverse order
    # would leave a stale-HIGH watermark if the shrink hit disk and the
    # lowered watermark didn't, bricking startup with a false
    # CorruptLogError.

    def _watermark_path(self) -> str:
        return os.path.join(self._dir, "synced")

    def _load_watermark(self) -> tuple[int, int]:  # graftcheck: holds(_lock)
        # CRC-guarded (see load_crc_watermark): garbage degrades to
        # (-1, 0) = nothing provably durable, which is always safe
        vals = load_crc_watermark(self._watermark_path(), 16)
        if vals is None:
            return (-1, 0)
        return struct.unpack("<qq", vals)

    def _save_watermark(self, sync: bool = False) -> None:  # graftcheck: holds(_lock)
        try:
            save_crc_watermark(self._watermark_path(), self._dir,
                               struct.pack("<qq", *self._synced), sync)
        except OSError:
            # sync=True saves are the destructive-op FLOORS (prefix/
            # suffix truncation, reset): a floor that can't be
            # persisted must abort its operation, so propagate.
            if sync:
                raise
            # every non-sync save ADVANCES the watermark after the
            # fact (init scan, clean shutdown, post-truncation
            # refresh), and stale-LOW is always safe — a full disk
            # (ENOSPC on synced.tmp) must not fail boot or shutdown
            # over a scan-avoidance optimization
            LOG.warning("watermark save failed (stale-LOW, non-fatal)",
                        exc_info=True)
            try:
                os.remove(self._watermark_path() + ".tmp")
            except OSError:
                pass

    def _meta_path(self) -> str:
        return os.path.join(self._dir, "meta")

    def _load_meta(self) -> None:  # graftcheck: holds(_lock)
        try:
            with open(self._meta_path(), "rb") as f:
                self._first = struct.unpack("<q", f.read(8))[0]
        except FileNotFoundError:
            self._first = 1

    def _save_meta(self) -> None:  # graftcheck: holds(_lock)
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(struct.pack("<q", self._first))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())
        _fsync_dir(self._dir)

    # conf sidecar: indexes of CONFIGURATION entries, so LogManager init
    # avoids an O(n) scan (reference: RocksDB conf column family)

    def _conf_path(self) -> str:
        return os.path.join(self._dir, "conf.idx")

    def _load_conf_indexes(self) -> None:  # graftcheck: holds(_lock)
        self._conf_indexes = []
        try:
            with open(self._conf_path(), "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return
        n = len(blob) // 8
        first, last = self._first, self.last_log_index()
        self._conf_indexes = [
            i
            for (i,) in struct.iter_unpack("<q", blob[: n * 8])
            if first <= i <= last
        ]

    def _rewrite_conf_indexes(self) -> None:  # graftcheck: holds(_lock)
        tmp = self._conf_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(b"".join(struct.pack("<q", i) for i in self._conf_indexes))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._conf_path())
        _fsync_dir(self._dir)

    def configuration_indexes(self) -> list[int]:
        with self._lock:
            return list(self._conf_indexes)

    # -- queries ------------------------------------------------------------

    def first_log_index(self) -> int:
        with self._lock:
            return self._first

    def last_log_index(self) -> int:
        with self._lock:
            if not self._segments:
                return self._first - 1
            return self._segments[-1].last_index

    def _find_segment(self, index: int) -> Optional[_Segment]:  # graftcheck: holds(_lock)
        lo, hi = 0, len(self._segments) - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            s = self._segments[mid]
            if index < s.first_index:
                hi = mid - 1
            elif index > s.last_index:
                lo = mid + 1
            else:
                return s
        return None

    def get_entry(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            if index < self._first:
                return None
            s = self._find_segment(index)
            return s.read(index) if s else None

    # -- mutations ----------------------------------------------------------

    def append_entries(self, entries: list[LogEntry], sync: bool = True) -> int:
        if not entries:
            return 0
        # The WHOLE mutation must hold the lock: this runs in executor
        # threads while the event loop reads get_entry on the same
        # segment file objects — an unlocked seek+write interleaving a
        # locked seek+read corrupts the read (and a misaligned frame can
        # silently return the WRONG entry to a replicator).  The fsync
        # happens OUTSIDE the lock (position-independent), so event-loop
        # readers never stall behind a disk flush.
        with self._lock:
            touched = self._append_entries_locked(entries, sync)
            if touched:
                # frontier candidate captured under the lock: only bytes
                # written BEFORE our fsync below may be claimed synced
                frontier = (touched[-1].first_index, touched[-1].size)
                # prefix durability: segments between the synced
                # frontier and this batch can carry staged-unsynced
                # bytes this batch never touched (an ENOSPC-failed
                # batch's landed prefix, with the segment rolled since).
                # Advancing the watermark over them without an fsync
                # would claim them durable — a crash then drops them,
                # leaving the acked suffix stranded past a hole.
                touched = [s for s in self._segments
                           if (s.first_index, s.size) > self._synced]
        # fsync oldest-first so a crash leaves a prefix, never a hole
        for seg in touched:
            seg.sync()
        if touched:
            with self._lock:
                if frontier > self._synced:
                    self._synced = frontier
        return len(entries)

    def _append_entries_locked(self, entries: list[LogEntry],
                               sync: bool) -> list["_Segment"]:
        expected = self.last_log_index() + 1
        if entries[0].id.index != expected:
            raise ValueError(
                f"non-contiguous append: have last={expected - 1}, got {entries[0].id.index}"
            )
        touched: list[_Segment] = []
        new_conf = False
        for e in entries:
            if not self._segments or self._segments[-1].size >= self._seg_max:
                seg = _Segment(
                    os.path.join(self._dir, f"seg_{e.id.index}.log"), e.id.index
                )
                seg.open()
                _fsync_dir(self._dir)
                self._segments.append(seg)
            cur = self._segments[-1]
            cur.append(e.encode())
            if not touched or touched[-1] is not cur:
                touched.append(cur)
            if e.type == EntryType.CONFIGURATION:
                self._conf_indexes.append(e.id.index)
                new_conf = True
        if new_conf:
            # sidecar BEFORE the entry fsync: a crash in between leaves a
            # sidecar index beyond last_log_index, which init's
            # first<=i<=last filter drops; the reverse order would
            # permanently hide a durable CONFIGURATION entry
            self._rewrite_conf_indexes()
        return touched if sync else []

    def truncate_prefix(self, first_index_kept: int) -> None:
        with self._lock:
            self._truncate_prefix_locked(first_index_kept)

    def _truncate_prefix_locked(self, first_index_kept: int) -> None:
        if first_index_kept <= self._first:
            return
        self._first = first_index_kept
        self._save_meta()
        if any(s.last_index < first_index_kept for s in self._segments):
            # The persisted watermark is only rewritten at init/shutdown/
            # destructive ops, so it can still name a segment this
            # compaction is about to delete (arbitrarily stale-low).
            # Persist the LIVE frontier — fsynced — BEFORE deleting:
            # otherwise a crash after the deletes leaves a watermark
            # pointing at a vanished segment, and the next init() raises
            # a false "watermark segment missing / acked entries lost"
            # on a perfectly healthy replica.  If the frontier segment
            # ITSELF sits inside the doomed range, CLEAR the watermark:
            # everything provably durable is being deleted, surviving
            # segments carry no durable claims (the frontier never
            # reached them — e.g. a sync=False run), so (-1, 0) loses
            # nothing — while naming any survivor would claim the
            # never-fsynced segments below it fully durable, and a crash
            # mid-delete would leave one to fail the _DURABLE_ALL scan
            # (the stale-HIGH false brick this function exists to avoid).
            survivor = next((s for s in self._segments
                             if s.last_index >= first_index_kept), None)
            if self._synced != (-1, 0) and (
                    survivor is None
                    or self._synced[0] < survivor.first_index):
                self._synced = (-1, 0)
            self._save_watermark(sync=True)
        # background-safe: delete whole segments strictly below the kept index
        while self._segments and self._segments[0].last_index < first_index_kept:
            self._segments.pop(0).delete()
        if self._conf_indexes and self._conf_indexes[0] < first_index_kept:
            self._conf_indexes = [i for i in self._conf_indexes if i >= first_index_kept]
            self._rewrite_conf_indexes()

    def truncate_suffix(self, last_index_kept: int) -> None:
        with self._lock:
            self._truncate_suffix_locked(last_index_kept)

    def _truncate_suffix_locked(self, last_index_kept: int) -> None:
        # find the segment that will remain active and FLOOR the
        # watermark to (its start, 0) — fsynced — BEFORE shrinking any
        # file: if the shrink hits disk and a later watermark write
        # doesn't, a stale-HIGH watermark would turn this legitimate
        # truncation into a false CorruptLogError at the next boot.
        target = next((s for s in reversed(self._segments)
                       if s.first_index <= last_index_kept), None)
        floor = (target.first_index, 0) if target else (-1, 0)
        if floor < self._synced:
            self._synced = floor
            self._save_watermark(sync=True)
        while self._segments and self._segments[-1].first_index > last_index_kept:
            self._segments.pop().delete()
        if self._segments:
            self._segments[-1].truncate_to(last_index_kept)
            # fsync even when truncate_to was a no-op (boundary case):
            # the watermark below claims this segment's bytes durable
            self._segments[-1].sync()
            self._synced = (self._segments[-1].first_index,
                            self._segments[-1].size)
        self._save_watermark()
        if self._conf_indexes and self._conf_indexes[-1] > last_index_kept:
            self._conf_indexes = [i for i in self._conf_indexes if i <= last_index_kept]
            self._rewrite_conf_indexes()

    def reset(self, next_log_index: int) -> None:
        with self._lock:
            self._reset_locked(next_log_index)

    def _reset_locked(self, next_log_index: int) -> None:
        # clear the watermark (fsynced) BEFORE deleting files: a crash
        # mid-delete must not leave a watermark pointing into a
        # partially-removed chain (false corruption alarm on reopen)
        self._synced = (-1, 0)
        self._save_watermark(sync=True)
        for s in self._segments:
            s.delete()
        self._segments.clear()
        self._first = next_log_index
        self._conf_indexes = []
        self._rewrite_conf_indexes()
        self._save_meta()


def _split_seg(rest: str) -> tuple[str, Optional[int]]:
    """Split an optional ``?seg=<bytes>`` suffix off a storage path.

    The suffix caps the segment size — prefix compaction returns disk
    in whole-segment units, so small segments make reclaim prompt
    enough for tight storage budgets (the disk-pressure plane)."""
    if "?seg=" not in rest:
        return rest, None
    path, _, val = rest.rpartition("?seg=")
    return path, int(val)


def create_log_storage(uri: str) -> LogStorage:
    """SPI-style factory by URI scheme (reference: DefaultJRaftServiceFactory
    #createLogStorage via JRaftServiceLoader)."""
    if uri == "memory://":
        return MemoryLogStorage()
    if uri.startswith("file://"):
        path, seg = _split_seg(uri[len("file://"):])
        return FileLogStorage(path, segment_max_bytes=seg)
    if uri.startswith("native://"):
        try:
            from tpuraft.storage.native_log import NativeLogStorage
        except ImportError as exc:
            raise ValueError(
                "native:// log storage requires the C++ engine "
                "(build with `make -C native`); falling back is deliberate "
                f"not automatic: {exc}"
            ) from exc
        path, seg = _split_seg(uri[len("native://"):])
        return NativeLogStorage(path, segment_max_bytes=seg or None)
    if uri.startswith("multilog://"):
        # shared multi-group journal engine: multilog://<dir>#<group_id>
        # — every group of a process shares one engine and one fsync per
        # flush round (tpuraft.storage.multilog)
        rest = uri[len("multilog://"):]
        if "#" not in rest:
            raise ValueError(
                "multilog:// needs a group fragment: multilog://<dir>#<group>")
        dir_path, group = rest.rsplit("#", 1)
        from tpuraft.storage.multilog import MultiLogStorage

        return MultiLogStorage(dir_path, group)
    raise ValueError(f"unknown log storage uri: {uri}")
