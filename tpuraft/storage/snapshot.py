"""Local snapshot storage: writers, readers, atomic commit, remote serving.

Reference parity (SURVEY.md §3.1 "Snapshot subsystem"):
``LocalSnapshotStorage`` (temp dir -> atomic rename ``snapshot_<index>``),
``LocalSnapshotWriter``/``Reader``, ``LocalSnapshotMetaTable`` (manifest
with per-file checksums), ``SnapshotFileReader`` (chunked remote serving
for ``GetFileRequest``).

Layout::

    <root>/temp/                  in-progress writer dir
    <root>/snapshot_<index>/      committed snapshots
        __snapshot_meta           manifest: SnapshotMeta + file table
        <user files...>
"""

from __future__ import annotations

import asyncio
import os
import shutil
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional

from tpuraft.rpc.messages import SnapshotMeta

_MANIFEST = "__snapshot_meta"


class ThroughputSnapshotThrottle:
    """Byte-rate throttle for snapshot file copy.

    Reference parity: ``core:storage/ThroughputSnapshotThrottle`` —
    caps install-snapshot bandwidth so a bulk file copy can't starve
    the log-replication traffic sharing the transport.  Token bucket
    with a one-second burst capacity; the file service asks it how many
    of the requested bytes may be served *now* and awaits the rest.
    """

    def __init__(self, bytes_per_sec: int, clock=time.monotonic):
        assert bytes_per_sec > 0
        self._rate = float(bytes_per_sec)
        self._avail = float(bytes_per_sec)
        self._clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._avail = min(self._rate, self._avail + (now - self._last) * self._rate)
        self._last = now

    def throttled_by_throughput(self, n: int) -> int:
        """Take up to ``n`` bytes from the bucket; returns the granted count."""
        self._refill()
        take = min(n, int(self._avail))
        self._avail -= take
        return take

    async def acquire_upto(self, n: int) -> int:
        """Await until at least one byte is available, then grant <= n."""
        if n <= 0:
            return 0
        while True:
            take = self.throttled_by_throughput(n)
            if take > 0:
                return take
            # time until one byte refills (bounded for clock hiccups)
            await asyncio.sleep(min(0.1, max(1.0 / self._rate, 1e-4)))


@dataclass
class _FileRecord:
    name: str
    size: int
    crc: int


def _encode_manifest(meta: SnapshotMeta, files: list[_FileRecord]) -> bytes:
    mb = meta.encode()
    out = bytearray(struct.pack("<I", len(mb)) + mb)
    out += struct.pack("<H", len(files))
    for f in files:
        nb = f.name.encode()
        out += struct.pack("<H", len(nb)) + nb + struct.pack("<qI", f.size, f.crc)
    body = bytes(out)
    return struct.pack("<I", zlib.crc32(body)) + body


def _decode_manifest(blob: bytes) -> tuple[SnapshotMeta, list[_FileRecord]]:
    (crc,) = struct.unpack_from("<I", blob, 0)
    body = blob[4:]
    if zlib.crc32(body) != crc:
        raise ValueError("snapshot manifest crc mismatch")
    (mlen,) = struct.unpack_from("<I", body, 0)
    meta = SnapshotMeta.decode(body[4 : 4 + mlen])
    off = 4 + mlen
    (nfiles,) = struct.unpack_from("<H", body, off)
    off += 2
    files = []
    for _ in range(nfiles):
        (nlen,) = struct.unpack_from("<H", body, off)
        off += 2
        name = body[off : off + nlen].decode()
        off += nlen
        size, fcrc = struct.unpack_from("<qI", body, off)
        off += 12
        files.append(_FileRecord(name, size, fcrc))
    return meta, files


class SnapshotWriter:
    def __init__(self, temp_dir: str):
        self._dir = temp_dir
        self._files: list[_FileRecord] = []
        os.makedirs(temp_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return self._dir

    def write_file(self, name: str, data: bytes) -> None:
        """Write one snapshot file (FSM-facing API)."""
        assert "/" not in name and name != _MANIFEST
        p = os.path.join(self._dir, name)
        with open(p, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        self._files.append(_FileRecord(name, len(data), zlib.crc32(data)))

    def add_file(self, name: str) -> None:
        """Register a file the FSM wrote directly into writer.path."""
        p = os.path.join(self._dir, name)
        with open(p, "rb") as f:
            data = f.read()
        self._files.append(_FileRecord(name, len(data), zlib.crc32(data)))

    def list_files(self) -> list[str]:
        return [f.name for f in self._files]

    def save_meta(self, meta: SnapshotMeta) -> None:
        blob = _encode_manifest(meta, self._files)
        p = os.path.join(self._dir, _MANIFEST)
        with open(p, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())


class SnapshotReader:
    def __init__(self, snapshot_dir: str):
        self._dir = snapshot_dir
        with open(os.path.join(snapshot_dir, _MANIFEST), "rb") as f:
            self.meta, self._files = _decode_manifest(f.read())

    @property
    def path(self) -> str:
        return self._dir

    def load_meta(self) -> SnapshotMeta:
        return self.meta

    def list_files(self) -> list[str]:
        return [f.name for f in self._files]

    def files(self) -> list[_FileRecord]:
        """Manifest records (name/size/crc) — the filter-before-copy key."""
        return list(self._files)

    def total_size(self) -> int:
        return sum(f.size for f in self._files)

    def read_file(self, name: str) -> Optional[bytes]:
        rec = next((f for f in self._files if f.name == name), None)
        if rec is None:
            return None
        with open(os.path.join(self._dir, name), "rb") as f:
            data = f.read()
        if zlib.crc32(data) != rec.crc:
            raise IOError(f"snapshot file {name} crc mismatch")
        return data

    # chunked access for remote copy (reference: SnapshotFileReader)
    def read_chunk(self, name: str, offset: int, count: int
                   ) -> tuple[bytes, bool]:
        if name == _MANIFEST:
            p = os.path.join(self._dir, _MANIFEST)
        else:
            rec = next((f for f in self._files if f.name == name), None)
            if rec is None:
                raise FileNotFoundError(name)
            p = os.path.join(self._dir, name)
        with open(p, "rb") as f:
            f.seek(offset)
            data = f.read(count)
            eof = f.tell() >= os.path.getsize(p)
        return data, eof


class LocalSnapshotStorage:
    """Reference: LocalSnapshotStorage — atomic temp->snapshot_<index>."""

    def __init__(self, root: str):
        self._root = root
        # byte deltas of the most recent commit (committed dir size,
        # bytes reclaimed by the prune) — the SnapshotExecutor reads
        # these into the store's DiskBudget; plain attrs, single commit
        # in flight per storage (the executor serializes saves)
        self.last_commit_bytes = 0
        self.last_reclaimed_bytes = 0

    def init(self) -> None:
        os.makedirs(self._root, exist_ok=True)
        # a leftover temp dir is an aborted snapshot: discard
        tmp = os.path.join(self._root, "temp")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        # sweep orphans a crash mid-commit leaves behind: stale
        # snapshot_<N> dirs older than the newest LOADABLE one (the
        # prune after os.replace never ran), and unreadable newer dirs
        # (replace landed but the manifest never got durable).  Without
        # this they leak until the disk fills — the disk-pressure soak
        # finds the leak first.
        dirs = self._snapshot_dirs()
        newest_valid = None
        for idx, path in reversed(dirs):
            try:
                SnapshotReader(path)
                newest_valid = idx
                break
            except (IOError, ValueError):
                continue
        if newest_valid is None:
            return  # nothing loadable: keep everything for forensics
        for idx, path in dirs:
            if idx != newest_valid:
                shutil.rmtree(path, ignore_errors=True)

    def _snapshot_dirs(self) -> list[tuple[int, str]]:
        out = []
        for n in os.listdir(self._root):
            if n.startswith("snapshot_"):
                try:
                    out.append((int(n[len("snapshot_"):]),
                                os.path.join(self._root, n)))
                except ValueError:
                    continue
        return sorted(out)

    def create(self) -> SnapshotWriter:
        tmp = os.path.join(self._root, "temp")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        return SnapshotWriter(tmp)

    @staticmethod
    def _dir_bytes(path: str) -> int:
        total = 0
        try:
            for n in os.listdir(path):
                try:
                    total += os.path.getsize(os.path.join(path, n))
                except OSError:
                    pass
        except OSError:
            pass
        return total

    def commit(self, writer: SnapshotWriter, meta: SnapshotMeta) -> str:
        writer.save_meta(meta)
        dst = os.path.join(self._root, f"snapshot_{meta.last_included_index}")
        if os.path.exists(dst):
            shutil.rmtree(dst)
        os.replace(writer.path, dst)
        fd = os.open(self._root, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        self.last_commit_bytes = self._dir_bytes(dst)
        # keep only the newest snapshot (reference keeps last 1 by default)
        reclaimed = 0
        for idx, path in self._snapshot_dirs()[:-1]:
            reclaimed += self._dir_bytes(path)
            shutil.rmtree(path, ignore_errors=True)
        self.last_reclaimed_bytes = reclaimed
        return dst

    def open(self) -> Optional[SnapshotReader]:
        dirs = self._snapshot_dirs()
        if not dirs:
            return None
        # newest first; skip corrupt ones
        for idx, path in reversed(dirs):
            try:
                return SnapshotReader(path)
            except (IOError, ValueError):
                import logging

                logging.getLogger(__name__).exception(
                    "corrupt snapshot at %s; trying older", path)
                continue
        return None


class RemoteFileCopier:
    """Follower-side chunked download of a remote snapshot
    (reference: remote/RemoteFileCopier over GetFileRequest)."""

    def __init__(self, transport, endpoint: str, reader_id: int,
                 chunk_size: int = 1 << 20):
        self._transport = transport
        self._endpoint = endpoint
        self._reader_id = reader_id
        self._chunk = chunk_size

    async def copy_to(self, filename: str, dst_path: str) -> int:
        from tpuraft.rpc.messages import GetFileRequest

        offset = 0
        with open(dst_path, "wb") as f:
            while True:
                resp = await self._transport.get_file(
                    self._endpoint,
                    GetFileRequest(reader_id=self._reader_id,
                                   filename=filename, offset=offset,
                                   count=self._chunk))
                f.write(resp.data)
                offset += len(resp.data)
                if resp.eof:
                    break
            f.flush()
            os.fsync(f.fileno())
        return offset

    async def read_bytes(self, filename: str) -> bytes:
        from tpuraft.rpc.messages import GetFileRequest

        out = bytearray()
        while True:
            resp = await self._transport.get_file(
                self._endpoint,
                GetFileRequest(reader_id=self._reader_id, filename=filename,
                               offset=len(out), count=self._chunk))
            out += resp.data
            if resp.eof:
                return bytes(out)
