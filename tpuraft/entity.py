"""Core entities: PeerId, LogId, LogEntry, Task.

Reference parity (SURVEY.md §3.1 "Entities & conf"):
``core:entity/PeerId`` (``ip:port[:idx[:priority]]`` parsing),
``core:entity/LogId{index,term}``, ``core:entity/LogEntry`` with CRC
checksum, ``core:entity/Task{data,done,expectedTerm}``.

Design difference from the reference: entries carry an explicit binary
codec (``encode``/``decode``) used by both the Python file log storage and
the C++ storage engine — one on-disk/wire format, no protobuf dependency in
the hot path.  Indexes/terms are unbounded Python ints on the host; the
device plane (tpuraft.ops) works in *base-relative* int32 space.
"""

from __future__ import annotations

import enum
import functools
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional


class ElectionPriority:
    """Priority election values (reference: ``core:entity/ElectionPriority``)."""

    DISABLED = -1   # priority election disabled for this node
    NOT_ELECTED = 0 # node never takes part in election
    MIN_VALUE = 1


@dataclass(frozen=True, order=True)
class PeerId:
    """A participant endpoint: ``ip:port[:idx[:priority]]``.

    Reference: ``core:entity/PeerId#parse``.  ``idx`` distinguishes
    multiple nodes of one process sharing an endpoint; ``priority`` feeds
    priority-based election (``[1.3+]``).
    """

    ip: str = "0.0.0.0"
    port: int = 0
    idx: int = 0
    priority: int = ElectionPriority.DISABLED

    @staticmethod
    def parse(s: str) -> "PeerId":
        parts = s.strip().split(":")
        if len(parts) < 2 or len(parts) > 4:
            raise ValueError(f"invalid peer id: {s!r}")
        ip = parts[0]
        port = int(parts[1])
        idx = int(parts[2]) if len(parts) >= 3 else 0
        priority = int(parts[3]) if len(parts) == 4 else ElectionPriority.DISABLED
        return PeerId(ip, port, idx, priority)

    def is_empty(self) -> bool:
        return self.ip == "0.0.0.0" and self.port == 0 and self.idx == 0

    # endpoint and str() are on the per-beat/per-request hot paths
    # (every heartbeat builds both); cache the formatted strings on the
    # frozen instance (cached_property writes __dict__ directly, which
    # bypasses the frozen __setattr__) — eq/hash/order use declared
    # fields only, so the memo never affects identity
    @functools.cached_property
    def endpoint(self) -> str:
        return f"{self.ip}:{self.port}"

    @functools.cached_property
    def _str(self) -> str:
        s = f"{self.ip}:{self.port}"
        if self.priority != ElectionPriority.DISABLED:
            return f"{s}:{self.idx}:{self.priority}"
        if self.idx != 0:
            return f"{s}:{self.idx}"
        return s

    def __str__(self) -> str:
        return self._str


EMPTY_PEER = PeerId()


@dataclass(frozen=True, order=True)
class LogId:
    """(index, term) pair; ordering is by index then term.

    Reference: ``core:entity/LogId``. Raft log comparison for elections
    compares term first, index second — use :meth:`newer_than` for that.
    """

    index: int = 0
    term: int = 0

    def newer_than(self, other: "LogId") -> bool:
        """Election log-up-to-date comparison (term first, then index)."""
        return (self.term, self.index) > (other.term, other.index)

    def __str__(self) -> str:
        return f"LogId[index={self.index}, term={self.term}]"


class EntryType(enum.IntEnum):
    """Reference: ``EnumOutter.EntryType``."""

    NO_OP = 0
    DATA = 1
    CONFIGURATION = 2


# On-disk / wire header for a log entry:
#   magic(1) type(1) reserved(2) term(8) index(8) npeers(2) nold(2)
#   data_len(4) crc32(4)  => 32 bytes, then peers blob, then data.
_HDR = struct.Struct("<BBHqqHHII")
_MAGIC = 0xB8
# decode-path enum lookup (EntryType(x) costs an enum __call__ — ~10%
# of per-entry decode on the replication hot path)
_ETYPES = {m.value: m for m in EntryType}


@dataclass
class LogEntry:
    """A replicated log entry.

    Reference: ``core:entity/LogEntry`` (+ v2 codec ``core:entity/codec/*``).
    CONFIGURATION entries carry ``peers``/``old_peers`` (joint consensus)
    and ``learners``/``old_learners``.
    """

    type: EntryType = EntryType.NO_OP
    id: LogId = field(default_factory=LogId)
    data: bytes = b""
    peers: Optional[list[PeerId]] = None
    old_peers: Optional[list[PeerId]] = None
    learners: Optional[list[PeerId]] = None
    old_learners: Optional[list[PeerId]] = None
    # witness voters (subset of peers/old_peers) — TRAILING extension of
    # the peers blob: entries without witnesses encode bit-identically
    # to the pre-witness format, and an old decoder reading a
    # witness-bearing entry ignores the trailing lists (the witness
    # degrades to a plain voter on old replicas — safe: quorum math is
    # identical, only the payload-stripping optimization is lost)
    witnesses: Optional[list[PeerId]] = None
    old_witnesses: Optional[list[PeerId]] = None
    # trace plane: the originating op's trace context (util/trace).
    # TRANSIENT — never encoded into the journal or the entry codec;
    # the wire carries it as an AppendEntriesRequest TRAILING field so
    # follower append/flush spans join the leader-side trace.  Excluded
    # from equality: a wire-decoded entry must still compare equal to
    # its storage-decoded twin.
    trace_id: int = field(default=0, compare=False, repr=False)

    # -- codec ---------------------------------------------------------------

    def encode(self) -> bytes:
        # Entries are encoded several times on the hot path (log flush +
        # once per follower); the blob is cached per LogId — staging
        # assigns the id once, after which the entry is logically
        # immutable (mirrors the reference encoding entries once into
        # pooled buffers via ByteBufferCollector).
        cached = self.__dict__.get("_enc")
        if cached is not None and cached[0] == self.id:
            return cached[1]
        if (self.peers is None and self.old_peers is None
                and self.learners is None and self.old_learners is None
                and self.witnesses is None and self.old_witnesses is None):
            peers_blob = b""  # DATA/NO_OP fast path (the hot case)
        else:
            peers_blob = _encode_peer_lists(
                self.peers, self.old_peers, self.learners,
                self.old_learners, self.witnesses, self.old_witnesses
            )
        crc = zlib.crc32(self.data)
        crc = zlib.crc32(peers_blob, crc)
        hdr = _HDR.pack(
            _MAGIC,
            self.type.value,
            0,
            self.id.term,
            self.id.index,
            len(peers_blob),
            0,
            len(self.data),
            crc,
        )
        blob = hdr + peers_blob + self.data
        self._enc = (self.id, blob)
        return blob

    @staticmethod
    def decode(buf: bytes | memoryview, verify: bool = True) -> "LogEntry":
        """Decode one entry blob.

        verify=False skips the CRC check — for the RPC WIRE path only
        (TCP is checksummed end-to-end, and the receiver's journal
        computes its own record CRC at write time), where per-entry CRC
        was ~10% of a follower's CPU.  Storage reads always verify:
        disk corruption is the threat this CRC exists for.
        """
        raw = buf if isinstance(buf, bytes) else bytes(buf)
        if len(raw) < _HDR.size:
            raise ValueError(f"log entry truncated: {len(raw)} < {_HDR.size} bytes")
        (magic, etype, _rsv, term, index, peers_len, _n2, data_len, crc) = \
            _HDR.unpack_from(raw)
        if _HDR.size + peers_len + data_len != len(raw):
            raise ValueError(
                f"log entry size mismatch: header says "
                f"{_HDR.size + peers_len + data_len}, have {len(raw)}"
            )
        if magic != _MAGIC:
            raise ValueError(f"bad log entry magic: {magic:#x}")
        off = _HDR.size
        data = raw[off + peers_len:]
        if peers_len:
            peers_blob = raw[off: off + peers_len]
            if verify and zlib.crc32(peers_blob, zlib.crc32(data)) != crc:
                raise ValueError(f"log entry crc mismatch at index {index}")
            (peers, old_peers, learners, old_learners,
             witnesses, old_witnesses) = _decode_peer_lists(peers_blob)
        else:
            if verify and zlib.crc32(data) != crc:
                raise ValueError(f"log entry crc mismatch at index {index}")
            peers = old_peers = learners = old_learners = None
            witnesses = old_witnesses = None
        # direct construction (object.__new__): the dataclass __init__'s
        # 7-kwarg dispatch was measurable at replication rates
        etype_m = _ETYPES.get(etype)
        if etype_m is None:
            # ValueError, like EntryType(etype) raised: the storage
            # recovery scan truncates torn tails on (ValueError,
            # struct.error) — a KeyError would crash startup instead
            raise ValueError(f"bad log entry type: {etype}")
        e = object.__new__(LogEntry)
        e.type = etype_m
        eid = LogId(index, term)
        e.id = eid
        e.data = data
        e.peers = peers
        e.old_peers = old_peers
        e.learners = learners
        e.old_learners = old_learners
        e.witnesses = witnesses
        e.old_witnesses = old_witnesses
        # pre-seed the encode cache with the exact source blob: the
        # entry re-encodes bit-identically (follower staging to the
        # journal, leader fan-out) without paying the codec again
        e._enc = (eid, raw)
        if not verify:
            # mark for the one deferred check at storage-staging time:
            # TCP's 16-bit checksum is weak, and a corrupt blob staged
            # bit-identically would only surface at the NEXT recovery
            # scan — as a spurious "torn tail" truncating acked entries
            e._crc_unverified = True
        return e

    def verify_crc(self) -> None:
        """One deferred CRC check against the cached wire blob.

        Raises ValueError on mismatch.  No-op for locally-built entries
        (``encode`` computes a fresh CRC) and for already-verified ones.
        """
        if not self.__dict__.get("_crc_unverified"):
            return
        cached = self.__dict__.get("_enc")
        if cached is None or cached[0] != self.id:
            self._crc_unverified = False
            return  # will re-encode with a fresh CRC anyway
        raw = cached[1]
        (_m, _t, _r, _term, _idx, peers_len, _n2, _dlen, crc) = \
            _HDR.unpack_from(raw)
        computed = zlib.crc32(raw[_HDR.size + peers_len:])
        if peers_len:
            computed = zlib.crc32(raw[_HDR.size:_HDR.size + peers_len], computed)
        if computed != crc:
            raise ValueError(
                f"log entry crc mismatch at index {self.id.index} (wire)")
        self._crc_unverified = False

    def encoded_size(self) -> int:
        return _HDR.size + len(
            _encode_peer_lists(self.peers, self.old_peers, self.learners,
                               self.old_learners, self.witnesses,
                               self.old_witnesses)
        ) + len(self.data)

    def is_configuration(self) -> bool:
        return self.type == EntryType.CONFIGURATION


def _encode_peer_lists(*lists: Optional[list[PeerId]]) -> bytes:
    """Encode up to 6 peer lists (peers, old_peers, learners,
    old_learners[, witnesses, old_witnesses]).  The witness pair is a
    TRAILING extension: omitted entirely when both are None, so
    witness-free entries keep the exact pre-witness byte format (old
    decoders read 4 lists and ignore any trailing bytes)."""
    if all(l is None for l in lists):
        return b""
    base, tail = lists[:4], lists[4:]
    if all(l is None for l in tail):
        lists = base
    out = bytearray()
    for l in lists:
        if l is None:
            out += struct.pack("<h", -1)
        else:
            out += struct.pack("<h", len(l))
            for p in l:
                s = str(p).encode()
                out += struct.pack("<H", len(s)) + s
    return bytes(out)


def _decode_peer_lists(blob: bytes):
    if not blob:
        return None, None, None, None, None, None
    lists: list[Optional[list[PeerId]]] = []
    off = 0
    for _ in range(6):
        if len(lists) >= 4 and off >= len(blob):
            # pre-witness entry: trailing lists default to None
            lists.append(None)
            continue
        (n,) = struct.unpack_from("<h", blob, off)
        off += 2
        if n < 0:
            lists.append(None)
            continue
        cur = []
        for _ in range(n):
            (slen,) = struct.unpack_from("<H", blob, off)
            off += 2
            cur.append(PeerId.parse(blob[off : off + slen].decode()))
            off += slen
        lists.append(cur)
    return tuple(lists)  # type: ignore[return-value]


def strip_entry_payload(e: LogEntry) -> LogEntry:
    """Witness replication: a DATA entry's payload is replaced by an
    empty body, keeping (index, term) — the witness's metadata-only
    journal stores exactly what elections and quorum intersection need.
    CONFIGURATION entries (and their peer lists) pass through whole:
    membership IS metadata.  The wire blob's deferred CRC is verified
    first, so a corrupt frame cannot launder bad metadata into the
    journal via the strip."""
    if e.type != EntryType.DATA or not e.data:
        return e
    e.verify_crc()
    return LogEntry(type=e.type, id=e.id, data=b"")


@dataclass
class Task:
    """A user task to replicate: opaque ``data`` + completion callback.

    Reference: ``core:entity/Task``.  ``done`` is called with a Status when
    the entry commits (or fails); ``expected_term`` guards against applying
    under a different leadership than intended.
    """

    data: bytes = b""
    done: Optional[Callable[["Any"], None]] = None  # called with Status
    expected_term: int = -1
    # trace plane: carried onto the staged LogEntry (util/trace); 0 =
    # untraced (the steady state)
    trace_id: int = field(default=0, compare=False, repr=False)
    # pipelined apply (write plane): ``done`` fires the moment the entry
    # COMMITS instead of after the FSM applies it — only valid for ops
    # whose result is known a priori (blind writes); the read-fence
    # machinery (read_index + wait_applied) keeps reads observing
    # applied state.  See FSMCaller's eager-ack path.
    ack_at_commit: bool = field(default=False, compare=False, repr=False)
