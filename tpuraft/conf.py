"""Cluster configuration tracking.

Reference parity (SURVEY.md §3.1): ``core:conf/Configuration`` (peer set +
learners, parse/diff), ``core:conf/ConfigurationEntry`` (conf at a log id,
with the *old* conf during joint consensus), ``core:conf/ConfigurationManager``
(ordered history of committed/appended conf entries so the log manager can
answer "what was the conf at index i").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from tpuraft.entity import LogId, PeerId


@dataclass
class Configuration:
    """A voter set plus optional learner (read-only replica) set."""

    peers: list[PeerId] = field(default_factory=list)
    learners: list[PeerId] = field(default_factory=list)

    @staticmethod
    def parse(conf_str: str) -> "Configuration":
        """Parse ``"ip:port,ip:port:idx,..."``; learners suffixed ``/learner``."""
        conf = Configuration()
        for tok in conf_str.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok.endswith("/learner"):
                conf.learners.append(PeerId.parse(tok[: -len("/learner")]))
            else:
                conf.peers.append(PeerId.parse(tok))
        return conf

    def copy(self) -> "Configuration":
        return Configuration(list(self.peers), list(self.learners))

    def is_empty(self) -> bool:
        return not self.peers

    def contains(self, peer: PeerId) -> bool:
        return peer in self.peers

    def is_valid(self) -> bool:
        """Voter and learner sets must be disjoint; no duplicate peers."""
        s = set(self.peers)
        return len(s) == len(self.peers) and not (s & set(self.learners))

    def quorum(self) -> int:
        return len(self.peers) // 2 + 1

    def diff(self, other: "Configuration") -> tuple[set[PeerId], set[PeerId]]:
        """Returns (added, removed) voter peers going self -> other."""
        a, b = set(self.peers), set(other.peers)
        return b - a, a - b

    def list_all(self) -> list[PeerId]:
        return list(self.peers) + list(self.learners)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Configuration):
            return NotImplemented
        return set(self.peers) == set(other.peers) and set(self.learners) == set(
            other.learners
        )

    def __str__(self) -> str:
        toks = [str(p) for p in sorted(self.peers)]
        toks += [f"{p}/learner" for p in sorted(self.learners)]
        return ",".join(toks)


@dataclass
class ConfigurationEntry:
    """The configuration in force at a given log id.

    During joint consensus (arbitrary ``changePeers``), ``old_conf`` is
    non-empty and decisions need a quorum of *both* sets — the device
    kernel's double-order-statistic path (tpuraft.ops.ballot).
    """

    id: LogId = field(default_factory=LogId)
    conf: Configuration = field(default_factory=Configuration)
    old_conf: Configuration = field(default_factory=Configuration)

    def is_stable(self) -> bool:
        return self.old_conf.is_empty()

    def contains(self, peer: PeerId) -> bool:
        return self.conf.contains(peer) or self.old_conf.contains(peer)

    def list_peers(self) -> list[PeerId]:
        return list({*self.conf.peers, *self.old_conf.peers})

    def copy(self) -> "ConfigurationEntry":
        return ConfigurationEntry(self.id, self.conf.copy(), self.old_conf.copy())


class ConfigurationManager:
    """Ordered history of configuration entries present in the log.

    Reference: ``core:conf/ConfigurationManager`` — supports truncation from
    either end (snapshot compaction / conflict truncation) and lookup of the
    latest conf at-or-before an index.
    """

    def __init__(self) -> None:
        self._configurations: list[ConfigurationEntry] = []
        self._snapshot = ConfigurationEntry()

    def add(self, entry: ConfigurationEntry) -> bool:
        if self._configurations and self._configurations[-1].id.index >= entry.id.index:
            return False
        self._configurations.append(entry)
        return True

    def truncate_prefix(self, first_index_kept: int) -> None:
        self._configurations = [
            e for e in self._configurations if e.id.index >= first_index_kept
        ]

    def truncate_suffix(self, last_index_kept: int) -> None:
        self._configurations = [
            e for e in self._configurations if e.id.index <= last_index_kept
        ]

    def set_snapshot(self, entry: ConfigurationEntry) -> None:
        if entry.id.index >= self._snapshot.id.index:
            self._snapshot = entry

    def get_snapshot(self) -> ConfigurationEntry:
        return self._snapshot

    def get(self, last_included_index: int) -> ConfigurationEntry:
        """Latest configuration whose log index <= last_included_index."""
        best: Optional[ConfigurationEntry] = None
        for e in self._configurations:
            if e.id.index <= last_included_index:
                best = e
            else:
                break
        if best is None:
            return self._snapshot.copy()
        return best.copy()

    def last(self) -> ConfigurationEntry:
        if self._configurations:
            return self._configurations[-1].copy()
        return self._snapshot.copy()
