"""Cluster configuration tracking.

Reference parity (SURVEY.md §3.1): ``core:conf/Configuration`` (peer set +
learners, parse/diff), ``core:conf/ConfigurationEntry`` (conf at a log id,
with the *old* conf during joint consensus), ``core:conf/ConfigurationManager``
(ordered history of committed/appended conf entries so the log manager can
answer "what was the conf at index i").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from tpuraft.entity import LogId, PeerId


@dataclass
class Configuration:
    """A voter set plus optional learner (read-only replica) set.

    **Witnesses** are VOTERS flagged in ``witnesses`` (a subset of
    ``peers``): they vote and ack appends — so every quorum computation
    over ``peers`` covers them transparently — but they store only log
    METADATA (payload-stripped appends), never campaign, and never
    serve reads.  A geo topology gets majority-cost commits without a
    full extra data copy (2 data + 1 witness = quorum 2).  Safety rests
    on two invariants checked in :meth:`is_valid` and enumerated in
    tests/oracle.py: at least one non-witness voter exists (leaders are
    always data replicas), and witnesses stay a strict minority so
    every majority contains a data replica.
    """

    peers: list[PeerId] = field(default_factory=list)
    learners: list[PeerId] = field(default_factory=list)
    witnesses: list[PeerId] = field(default_factory=list)  # subset of peers

    @staticmethod
    def parse(conf_str: str) -> "Configuration":
        """Parse ``"ip:port,ip:port:idx,..."``; learners suffixed
        ``/learner``, witness voters suffixed ``/witness``."""
        conf = Configuration()
        for tok in conf_str.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok.endswith("/learner"):
                conf.learners.append(PeerId.parse(tok[: -len("/learner")]))
            elif tok.endswith("/witness"):
                p = PeerId.parse(tok[: -len("/witness")])
                conf.peers.append(p)
                conf.witnesses.append(p)
            else:
                conf.peers.append(PeerId.parse(tok))
        return conf

    def copy(self) -> "Configuration":
        return Configuration(list(self.peers), list(self.learners),
                             list(self.witnesses))

    def is_empty(self) -> bool:
        return not self.peers

    def contains(self, peer: PeerId) -> bool:
        return peer in self.peers

    def is_witness(self, peer: PeerId) -> bool:
        return peer in self.witnesses

    def data_peers(self) -> list[PeerId]:
        """Voters that hold full log payloads (quorum durability)."""
        w = set(self.witnesses)
        return [p for p in self.peers if p not in w]

    def is_valid(self) -> bool:
        """Voter and learner sets must be disjoint; no duplicate peers.
        Witness invariants: witnesses ⊆ peers, at least one data voter
        exists, and witnesses are a strict MINORITY of the voter set
        (< quorum) so every majority contains a data replica — the rule
        is THE enumeration-verified ``util.quorum.witness_minority``
        (one predicate: the verified function IS the enforced one)."""
        from tpuraft.util.quorum import witness_minority

        s = set(self.peers)
        if len(s) != len(self.peers) or (s & set(self.learners)):
            return False
        if len(set(self.witnesses)) != len(self.witnesses):
            return False
        return witness_minority(s, self.witnesses)

    def quorum(self) -> int:
        return len(self.peers) // 2 + 1

    def diff(self, other: "Configuration") -> tuple[set[PeerId], set[PeerId]]:
        """Returns (added, removed) voter peers going self -> other."""
        a, b = set(self.peers), set(other.peers)
        return b - a, a - b

    def list_all(self) -> list[PeerId]:
        return list(self.peers) + list(self.learners)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Configuration):
            return NotImplemented
        return (set(self.peers) == set(other.peers)
                and set(self.learners) == set(other.learners)
                and set(self.witnesses) == set(other.witnesses))

    def __str__(self) -> str:
        w = set(self.witnesses)
        toks = [f"{p}/witness" if p in w else str(p)
                for p in sorted(self.peers)]
        toks += [f"{p}/learner" for p in sorted(self.learners)]
        return ",".join(toks)


@dataclass
class ConfigurationEntry:
    """The configuration in force at a given log id.

    During joint consensus (arbitrary ``changePeers``), ``old_conf`` is
    non-empty and decisions need a quorum of *both* sets — the device
    kernel's double-order-statistic path (tpuraft.ops.ballot).
    """

    id: LogId = field(default_factory=LogId)
    conf: Configuration = field(default_factory=Configuration)
    old_conf: Configuration = field(default_factory=Configuration)

    def is_stable(self) -> bool:
        return self.old_conf.is_empty()

    def contains(self, peer: PeerId) -> bool:
        return self.conf.contains(peer) or self.old_conf.contains(peer)

    def list_peers(self) -> list[PeerId]:
        return list({*self.conf.peers, *self.old_conf.peers})

    def copy(self) -> "ConfigurationEntry":
        return ConfigurationEntry(self.id, self.conf.copy(), self.old_conf.copy())


class ConfigurationManager:
    """Ordered history of configuration entries present in the log.

    Reference: ``core:conf/ConfigurationManager`` — supports truncation from
    either end (snapshot compaction / conflict truncation) and lookup of the
    latest conf at-or-before an index.
    """

    def __init__(self) -> None:
        self._configurations: list[ConfigurationEntry] = []
        self._snapshot = ConfigurationEntry()

    def add(self, entry: ConfigurationEntry) -> bool:
        if self._configurations and self._configurations[-1].id.index >= entry.id.index:
            return False
        self._configurations.append(entry)
        return True

    def truncate_prefix(self, first_index_kept: int) -> None:
        self._configurations = [
            e for e in self._configurations if e.id.index >= first_index_kept
        ]

    def truncate_suffix(self, last_index_kept: int) -> None:
        self._configurations = [
            e for e in self._configurations if e.id.index <= last_index_kept
        ]

    def set_snapshot(self, entry: ConfigurationEntry) -> None:
        if entry.id.index >= self._snapshot.id.index:
            self._snapshot = entry

    def get_snapshot(self) -> ConfigurationEntry:
        return self._snapshot

    def get(self, last_included_index: int) -> ConfigurationEntry:
        """Latest configuration whose log index <= last_included_index."""
        best: Optional[ConfigurationEntry] = None
        for e in self._configurations:
            if e.id.index <= last_included_index:
                best = e
            else:
                break
        if best is None:
            return self._snapshot.copy()
        return best.copy()

    def last(self) -> ConfigurationEntry:
        if self._configurations:
            return self._configurations[-1].copy()
        return self._snapshot.copy()
