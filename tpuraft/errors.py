"""Status / error model.

Reference parity: ``core:error/RaftError`` enum and ``core:Status`` —
every async operation completes with a Status; closures become awaitables
in this build (SURVEY.md §9).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class RaftError(enum.IntEnum):
    """Error codes, mirroring the reference's RaftError enum semantics."""

    SUCCESS = 0
    UNKNOWN = 1
    # Raft protocol errors
    ERAFTTIMEDOUT = 10001      # op timed out (election, replication...)
    ESTATEMACHINE = 10002      # user state machine raised
    ECATCHUP = 10003           # peer catch-up failed (membership change)
    ELEADERREMOVED = 10004     # leader removed from configuration
    ESETPEER = 10005           # bad set-peer request
    ENODESHUTTING = 10006      # node is shutting down
    EHIGHERTERMREQUEST = 10007 # saw request with higher term
    EHIGHERTERMRESPONSE = 10008
    EBADNODE = 10009
    EVOTEFORCANDIDATE = 10010
    ENEWLEADER = 10011         # a new leader emerged; pending ops invalidated
    ELEADERCONFLICT = 10012
    ETRANSFERLEADERSHIP = 10013
    ELOGDELETED = 10014        # log entry compacted away
    ENOMOREUSERLOG = 10015
    # generic posix-flavored errors the reference reuses
    EINVAL = 22
    EIO = 5
    EAGAIN = 11
    EINTR = 4
    EBUSY = 16
    ETIMEDOUT = 110
    EPERM = 1008
    EINTERNAL = 1004
    ECANCELED = 1009
    EHOSTDOWN = 112
    ESHUTDOWN = 108
    ENOENT = 2
    EEXISTS = 17
    # transport: no handler registered for the requested method.  A
    # DEDICATED code so capability probes (send plane / heartbeat hub
    # falling back to per-item RPCs against an older receiver) match on
    # the code, not on the wording of an error message.
    ENOMETHOD = 1010


@dataclass(frozen=True)
class Status:
    """Operation outcome: code + human message. ``Status.OK()`` is success."""

    code: int = 0
    error_msg: str = ""

    @staticmethod
    def OK() -> "Status":
        return _OK

    @staticmethod
    def error(code: RaftError | int, msg: str = "") -> "Status":
        code = int(code)
        if not msg:
            try:
                msg = RaftError(code).name
            except ValueError:
                msg = f"error {code}"
        return Status(code, msg)

    def is_ok(self) -> bool:
        return self.code == 0

    @property
    def raft_error(self) -> RaftError:
        try:
            return RaftError(self.code)
        except ValueError:
            return RaftError.UNKNOWN

    def __bool__(self) -> bool:  # truthy == ok, matches reference Status#isOk usage
        return self.is_ok()

    def __str__(self) -> str:
        if self.is_ok():
            return "Status[OK]"
        return f"Status[{self.raft_error.name}<{self.code}>: {self.error_msg}]"


_OK = Status(0, "")


class RaftException(Exception):
    """Raised for fatal errors that must stop a node (reference: RaftException)."""

    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status
