"""Options tree — nested dataclasses, the reference's builder/POJO options.

Reference parity (SURVEY.md §6 "Config / flag system"): ``NodeOptions``
(timeouts, storage URIs, state machine, initial conf) containing
``RaftOptions`` (engine tunables with the reference's defaults:
max_entries_size=1024, max_body_size=512KB, apply_batch=32,
max_inflight_msgs=256, pipelined replication, sync on write), plus
``ReadOnlyOption``.  TPU-specific knobs live in :class:`TickOptions`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, TYPE_CHECKING

from tpuraft.conf import Configuration

if TYPE_CHECKING:
    from tpuraft.core.state_machine import StateMachine


class ReadOnlyOption(enum.Enum):
    """Linearizable read mode (reference: ``ReadOnlyOption``)."""

    SAFE = "safe"               # quorum-confirmed ReadIndex round
    LEASE_BASED = "lease_based" # trust the leader lease (clock-dependent)


@dataclass
class RaftOptions:
    """Engine tunables; defaults mirror the reference's RaftOptions."""

    max_entries_size: int = 1024          # max entries per AppendEntries
    max_body_size: int = 512 * 1024       # max bytes per AppendEntries
    max_append_buffer_size: int = 256 * 1024  # log-storage flush batch bytes
    # Recent-entry window kept in RAM past stability/apply so replication
    # reads stay off disk (reference: maxLogsInMemory).  PER GROUP: a
    # process hosting G groups retains up to G x min(count, bytes) — the
    # bytes cap keeps thousand-group deployments bounded.
    max_logs_in_memory: int = 256
    max_logs_in_memory_bytes: int = 256 * 1024
    apply_batch: int = 32                 # tasks batched per apply event
    sync: bool = True                     # fsync log writes
    sync_meta: bool = True                # fsync term/votedFor changes
    replicator_pipeline: bool = True
    max_inflight_msgs: int = 256          # replication pipeline window
    max_election_delay_ms: int = 1000     # random election timeout jitter
    election_heartbeat_factor: int = 10   # heartbeat = election_timeout / factor
    # Coalesce leader heartbeats across ALL local raft groups into one
    # multi_heartbeat RPC per destination endpoint per interval (the
    # batched send-matrix plane — O(endpoints) instead of O(groups x
    # peers) idle RPCs).  Needs the node wired to a NodeManager.
    # None = AUTO (default): coalesce per peer once its AppendEntries
    # responses advertise the multi_heartbeat capability (the receiver
    # runs a NodeManager), direct beats otherwise — so a 1K-group idle
    # cluster's RPC rate is O(endpoints) out of the box.  True = always
    # (peers must serve multi_heartbeat), False = never.
    coalesce_heartbeats: Optional[bool] = None
    # Group quiescence ("hibernate raft"): an engine-driven leader group
    # that is fully replicated, has nothing pending, and sees this many
    # CONSECUTIVE fully-acked beat rounds hibernates — its beats and its
    # followers' election timeouts are suppressed on device, and liveness
    # is delegated to ONE store-level lease beat per endpoint pair
    # (HeartbeatHub), so an idle deployment's beat-plane RPC rate drops
    # from O(groups x peers) to O(stores^2).  Any apply / conf change /
    # incoming traffic instantly wakes the group; a store-lease expiry
    # wakes its dependent groups with randomized election timeouts.
    # 0 disables (the conservative default); 4-16 is a sensible range —
    # smaller = faster to hibernate, larger = more proof of idleness.
    # Engine-driven nodes only (TimerControl nodes never quiesce).
    quiesce_after_rounds: int = 0
    read_only_option: ReadOnlyOption = ReadOnlyOption.SAFE
    max_replicator_retry_times: int = 3
    step_down_when_vote_timedout: bool = True
    # priority election [1.3+]: minimum amount the target priority decays
    # by after a node skips consecutive election rounds (reference:
    # RaftOptions#decayPriorityGap)
    decay_priority_gap: int = 10
    # priority RE-election (geo): a leader whose own priority sits below
    # a healthy higher-priority voter's hands leadership back once that
    # voter has been caught up and acking for this many consecutive
    # step-down-timer rounds — so leadership returns to the preferred
    # zone after it heals instead of sticking where the decay left it.
    # 0 disables.  Only engages when the leader's priority is ENABLED.
    priority_transfer_rounds: int = 2
    # lease safety margin: leader lease = election_timeout * ratio
    leader_lease_time_ratio: float = 0.9
    # Assumed worst-case clock RATE error between any two stores
    # (rho): every lease the HOLDER trusts shrinks by (1 - rho), and
    # every lease a RECEIVER times against its own clock is padded the
    # same way, so sender and receiver disagreeing by up to rho per
    # second can never let a lease outlive its grant (ISSUE 18; see
    # docs/architecture.md "Lease safety under bounded drift").  Also
    # arms the ClockSentinel: a store whose clock deviates from the
    # peer median by MORE than rho fails lease checks closed (reads
    # fall back to the SAFE quorum path) until the estimate heals.
    # 0.0 = legacy zero-margin accounting, sentinel never fences.
    clock_drift_bound: float = 0.0


@dataclass
class TickOptions:
    """Device-plane knobs (no reference counterpart — TPU-native design).

    The multi-raft engine advances all groups on a tick cadence; each tick
    uploads one coalesced ``[G, P]`` delta and downloads one result batch
    (SURVEY.md §8 "host<->device latency budget").
    """

    max_groups: int = 1024        # G capacity of the state tensors
    max_peers: int = 8            # P: peer slots per group (voters+learners)
    # MAX idle interval between deadline scans.  The loop is adaptive:
    # a dirty mark (new acks/votes) fires a tick immediately, so commit
    # acks are not quantized to this cadence (VERDICT r1 weak #1).
    tick_interval_ms: int = 10
    # Pacing floor between CONSECUTIVE dirty-triggered ticks.  An ack
    # arriving while the engine is idle still fires its tick
    # immediately (sub-ms commit ack); the floor only bounds the
    # sustained tick rate so a busy engine batches instead of
    # monopolizing the event loop.  pace_factor x last tick's cost
    # additionally self-paces slow (tunneled) devices.
    min_tick_interval_ms: float = 1.0
    # Sleep pace_factor x (last tick duration) between consecutive
    # dirty ticks: cheap ticks run nearly back-to-back (sub-ms ack),
    # expensive ticks (tunneled device) batch more per dispatch.
    pace_factor: float = 0.5
    # Engine-driven protocol control plane: nodes whose ballot box comes
    # from this engine get elections / leases / step-down / heartbeat
    # scheduling from the fused device tick (tpuraft.ops.tick.raft_tick)
    # instead of per-group RepeatedTimers — the SURVEY §8.1 device
    # plane.  False = commit-reduce only (legacy: host timers).
    drive_protocol: bool = True
    # Event-driven commit advancement: an ack that completes a quorum
    # advances that group's commit point ON THE ACK PATH (one scalar
    # order statistic over the slot's [P] row — the same joint math the
    # device tick reduces) instead of waiting out the tick pace.  The
    # tick stays the batch plane and recomputes the same value as a
    # safety net.  False = tick-cadence commits (the pre-write-plane
    # behavior; also what the device-vs-oracle parity tests pin).
    eager_commit: bool = True
    # Density-aware timeout floors: the engine derives a minimum election
    # timeout from the REGISTERED group count and the measured tick
    # dispatch cost, and raises any group whose requested timeout sits
    # below it (hb/lease scale proportionally; the node's host-side
    # options adopt the raise).  Replaces the hand-tuned "60s at 16Kx3"
    # operating point: the floor keeps the idle beat plane under
    # ``beat_cpu_budget`` of one core at whatever density the process
    # actually reaches.  False = never raise (benchmarks of the raw
    # envelope; misconfigured densities then wedge exactly as before).
    density_aware_timeouts: bool = True
    # Estimated end-to-end cost of ONE beat row (sender build + RPC share
    # + receiver validate + ack bookkeeping), microseconds.  Seeded from
    # the measured beat-plane envelope (docs/operations.md "Scale
    # election timeouts with group density"); the engine additionally
    # folds its own measured tick cost into the floor, so a slow host
    # raises timeouts further than this constant alone would.
    beat_cost_us: float = 20.0
    # Fraction of one core the idle beat plane may consume before the
    # floor starts raising timeouts.
    beat_cpu_budget: float = 0.10
    # Injectable time source for the engine's tick deadlines / epoch
    # math (tpuraft.util.clock.Clock-shaped: .monotonic()/.wall()).
    # None = tpuraft.util.clock.SYSTEM (real time, zero-overhead path).
    clock: Optional[object] = None
    backend: str = "auto"         # "auto" | "jax" | "numpy" (numpy for tiny tests)
    donate_state: bool = True     # donate state buffers to the tick kernel
    # Shard the engine's [G, P] planes over a device mesh along the group
    # axis (0/1 = single device).  max_groups must divide evenly.  The
    # quorum reduce then runs SPMD across chips with the per-tick upload
    # scattered and the commit download gathered over ICI.
    mesh_devices: int = 0
    # Write an XLA profiler trace of the engine's device ticks into this
    # directory (viewable in TensorBoard / Perfetto — SURVEY.md §6
    # "tracing": jax.profiler traces for device ticks).  "" = off.
    # The trace spans from engine start to shutdown.  jax backends only
    # (ignored with a warning on backend="numpy"); the profiler is
    # process-global, so one engine per process can trace at a time.
    profile_dir: str = ""


@dataclass
class SnapshotOptions:
    interval_secs: int = 3600           # periodic snapshot cadence (reference default)
    log_index_margin: int = 0           # keep this many entries behind snapshot
    max_chunk_size: int = 1 << 20       # InstallSnapshot file chunk bytes
    throttle_bytes_per_sec: int = 0     # 0 = unthrottled (ThroughputSnapshotThrottle)


@dataclass
class NodeOptions:
    """Per-node options (reference: ``core:option/NodeOptions``)."""

    election_timeout_ms: int = 1000
    snapshot: SnapshotOptions = field(default_factory=SnapshotOptions)
    initial_conf: Configuration = field(default_factory=Configuration)
    fsm: Optional["StateMachine"] = None
    log_uri: str = ""            # "memory://" or "file://<dir>" or "native://<dir>"
    raft_meta_uri: str = ""
    snapshot_uri: str = ""       # empty = snapshots disabled
    disable_cli: bool = False
    enable_metrics: bool = True
    # witness replica: this node votes and acks appends but stores log
    # METADATA only (payload-stripped entries, null FSM, never
    # campaigns, never serves reads).  Set automatically by StoreEngine
    # when the node's own peer is '/witness'-flagged in the region conf.
    witness: bool = False
    catchup_margin: int = 1000   # membership-change catch-up threshold (entries)
    raft_options: RaftOptions = field(default_factory=RaftOptions)
    tick: TickOptions = field(default_factory=TickOptions)
    # store-level gray-failure tracker (tpuraft.util.health.
    # HealthTracker), shared by every node the hosting store runs: the
    # LogManager feeds its disk probe, the FSMCaller its apply depth,
    # heartbeat paths their peer RTTs, and the node's election gate
    # consults the score.  None = no health scoring (bare nodes).
    health: Optional[object] = None
    # store-level disk-capacity tracker (tpuraft.util.health.
    # DiskBudget), shared by every node the hosting store runs: the
    # LogManager feeds append bytes + ENOSPC observations, the snapshot
    # executor feeds commit/prune deltas, and the store's health task
    # reconciles + folds pressure.  None = no capacity accounting.
    disk_budget: Optional[object] = None
    # a SICK store skips this many consecutive election rounds before
    # campaigning anyway (the liveness escape when every peer is worse
    # off) — the election-priority face of gray-failure mitigation
    sick_election_rounds: int = 2
    # Injectable time source (tpuraft.util.clock: .monotonic()/.wall())
    # shared by everything timing-sensitive this node runs — election
    # timers, _last_leader_timestamp, lease math, health hysteresis.
    # StoreEngine threads ONE clock to every node it hosts so a
    # per-store clock fault (ChaosClock) skews the whole store
    # coherently.  None = tpuraft.util.clock.SYSTEM (real time).
    clock: Optional[object] = None
    # store-level clock sentinel (tpuraft.util.clock.ClockSentinel),
    # shared like ``health``: the HeartbeatHub feeds it beat-ack skew
    # probes and lease checks consult it to fail closed when the local
    # clock is drift-suspect.  None = no detection.
    clock_sentinel: Optional[object] = None
    # store-level FSM apply lane (tpuraft.core.lanes.WorkerLane), shared
    # by every node the hosting store runs: when set AND the FSM exposes
    # a sync ``apply_sync``, committed DATA runs execute on the lane
    # thread instead of the event loop (StoreEngineOptions.apply_lane).
    # The lane then OWNS the state the FSM mutates — all other access
    # must be submitted through it.  None = apply on the loop.
    apply_lane: Optional[object] = None


@dataclass
class CliOptions:
    timeout_ms: int = 3000
    max_retry: int = 3
    retry_interval_ms: int = 100
    # EBUSY ("another membership change in flight") gets its own bounded
    # exponential backoff budget: busy is transient-by-contract, unlike a
    # leader redirect, so it neither consumes max_retry nor drops the
    # cached leader
    busy_max_retry: int = 8
    busy_backoff_ms: int = 200
    busy_backoff_max_ms: int = 2000


@dataclass
class ReadIndexOptions:
    timeout_ms: int = 2000
    batch: int = 32
