"""Pre-merge perf gate (`make bench-gate`): a short `bench_e2e.py` run
at the committed BENCH_E2E.json's configuration must not regress e2e
commits/s by more than the threshold (default 20%).

The committed JSON is the contract, but the gate run is SHORT (boot +
elections amortize worse over a 6 s window than over a full bench), so
the floor is derived from a same-shape calibration value stored as
``extra.gate_commits_per_sec`` in BENCH_E2E.json — record it with
``python bench_gate.py --record`` on the host that runs the gate.
Without a calibration the gate falls back to the full-run ``value``
(conservative: short runs understate it, expect to re-record).

A run below the floor is retried (best-of-N, default 2 extra runs)
before the gate fails: a real regression makes EVERY run slow, while a
noisy-neighbour phase on a shared host does not survive three samples.
Exit 0 = within threshold, 1 = regression, 2 = the gate itself could
not run (missing baseline, bench crash) — a broken gate must read as
failure, not as a pass.

    python bench_gate.py                 # vs BENCH_E2E.json, 20%
    python bench_gate.py --record        # (re)calibrate the short-run
                                         # baseline into BENCH_E2E.json
    BENCH_GATE_THRESHOLD=0.3 python bench_gate.py   # looser (noisy CI)
    BENCH_GATE_RETRIES=0 python bench_gate.py       # strict single run
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.abspath(__file__))


def _run_once(extra: dict, duration: float) -> float:
    """One short bench_e2e run at the committed shape; returns commits/s
    or raises RuntimeError when the bench itself fails."""
    out_path = os.path.join(tempfile.mkdtemp(prefix="tpuraft_gate_"),
                            "gate.json")
    cmd = [sys.executable, os.path.join(REPO, "bench_e2e.py"),
           "--groups", str(extra.get("groups", 64)),
           "--stores", str(extra.get("stores", 3)),
           "--window", str(extra.get("window_per_group", 8)),
           "--payload", str(extra.get("payload_bytes", 16)),
           "--duration", str(duration), "--warmup", "2",
           "--skip-brk", "--json-out", out_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    print("bench-gate:", " ".join(cmd), flush=True)
    rc = subprocess.call(cmd, env=env)
    if rc != 0 or not os.path.exists(out_path):
        raise RuntimeError(f"bench run failed (rc={rc})")
    with open(out_path) as f:
        return float(json.load(f)["value"])


def main() -> int:
    base_path = os.path.join(REPO, "BENCH_E2E.json")
    if not os.path.exists(base_path):
        print("bench-gate: no committed BENCH_E2E.json baseline")
        return 2
    with open(base_path) as f:
        base = json.load(f)
    extra = base.get("extra", {})
    threshold = float(os.environ.get("BENCH_GATE_THRESHOLD", "0.20"))
    duration = float(os.environ.get("BENCH_GATE_DURATION", "6"))
    retries = int(os.environ.get("BENCH_GATE_RETRIES", "2"))

    if "--record" in sys.argv[1:]:
        # calibrate: best-of-2 short runs -> extra.gate_commits_per_sec
        try:
            best = max(_run_once(extra, duration) for _ in range(2))
        except RuntimeError as exc:
            print(f"bench-gate: {exc}")
            return 2
        extra["gate_commits_per_sec"] = round(best, 1)
        extra["gate_duration_s"] = duration
        base["extra"] = extra
        with open(base_path, "w") as f:
            json.dump(base, f, indent=1)
            f.write("\n")
        print(json.dumps({"gate": "recorded",
                          "gate_commits_per_sec": extra["gate_commits_per_sec"],
                          "duration_s": duration}))
        return 0

    committed = float(extra.get("gate_commits_per_sec", base["value"]))
    floor = committed * (1.0 - threshold)
    best, runs = 0.0, 0
    try:
        for attempt in range(1 + max(0, retries)):
            best = max(best, _run_once(extra, duration))
            runs = attempt + 1
            if best >= floor:
                break
            if attempt < retries:
                print(f"bench-gate: {best:.1f} < floor {floor:.1f}, "
                      f"retrying ({attempt + 1}/{retries})", flush=True)
    except RuntimeError as exc:
        print(f"bench-gate: {exc}")
        return 2
    verdict = "OK" if best >= floor else "REGRESSION"
    print(json.dumps({
        "gate": "e2e_commits_per_sec",
        "committed": committed,
        "measured": round(best, 1),
        "floor": round(floor, 1),
        "threshold": threshold,
        "runs": runs,
        "verdict": verdict,
    }))
    return 0 if best >= floor else 1


if __name__ == "__main__":
    sys.exit(main())
