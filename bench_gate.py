"""Pre-merge perf gate (`make bench-gate`): short bench runs at the
committed configurations must not regress by more than the threshold
(default 20%).

Rows:
  e2e_commits_per_sec — a short `bench_e2e.py` run vs BENCH_E2E.json
  engine_ticks_per_sec — the single-device engine tick rate at the
                        committed leader-heavy shape (bench_multichip
                        --engine-shape) vs BENCH_E2E.json
                        extra.gate_engine_ticks_per_sec, so the mesh-
                        mode work (ISSUE 19: witness clamp, stepdown
                        lane, fence tallies in every tick) can't tax
                        the single-device engine unnoticed.
  kv_ops_per_sec      — a short `bench_region_density.py` run (the full
                        RheaKV serving stack: batching client →
                        kv_command_batch → propose fan-out → coalesced
                        FSM apply) vs BENCH_REGIONS.json, so the
                        KV-vs-protocol throughput gap (ROADMAP item 1)
                        can't silently reopen.
  kv_read_ops_per_sec — the 95/5 read-mix shape vs its calibration.
  kv_write_ops_per_sec — the saturated pure-write shape (w256 @128
                        regions) vs its calibration, so write-plane
                        regressions (ISSUE 15's append rounds + eager
                        commits + ack-at-commit) gate like the rest.
  kv_mp_write_ops_per_sec — the SAME saturated pure-write shape with
                        each store a real OS process (bench_multiproc:
                        examples.proc_supervisor children over real
                        sockets) vs its calibration, so the process
                        fabric (ISSUE 16: READY probes, drain contract,
                        per-process CPU attribution) gates alongside
                        the in-process rows.  Calibration is same-host:
                        on a 1-CPU container the mp shape pays socket +
                        context-switch cost with no parallelism to buy,
                        and the floor reflects that honestly.
  kv_ops_traced       — tracing-overhead gate: the untraced rows above
                        run with the trace plane DISABLED (the
                        zero-cost claim — any always-on cost regresses
                        them vs calibration), and this row re-runs the
                        kv shape with 5%-sampled tracing, which must
                        stay within BENCH_GATE_TRACE_THRESHOLD
                        (default 5%) of the same-session untraced
                        measurement.
  kv_ops_heat_overhead — heat-accounting gate: per-region heat
                        tracking defaults ON, so the kv row already
                        pays for it; this row runs the same shape with
                        --no-heat and the heat-ON measurement must
                        stay within BENCH_GATE_HEAT_THRESHOLD
                        (default 3%) of the heat-OFF comparator.
  kv_ops_disk_guard   — disk-budget gate (ISSUE 17): the DiskBudget
                        accounting + admission check default ON, so
                        the kv row already pays for them; this row
                        runs the same shape with --no-disk-guard and
                        the guard-ON measurement must stay within
                        BENCH_GATE_DISK_THRESHOLD (default 2%) of the
                        guard-OFF comparator — the hot-path cost of
                        the pressure plane is a couple of integer adds
                        and one dict lookup, and this row keeps it so.
  kv_ops_clocked      — injected-clock gate (ISSUE 18): the default
                        rows run on the zero-indirection SYSTEM clock
                        (module-level staticmethods bound to the C
                        time functions), and this row re-runs the kv
                        shape with --chaos-clock (a per-store
                        ChaosClock at rate 1.0 — the full virtual-
                        clock arithmetic with no behavior change),
                        which must stay within
                        BENCH_GATE_CLOCK_THRESHOLD (default 2%) of
                        the same-session uninjected measurement.
  kv_ops_lifecycle_overhead — region-lifecycle gate (ISSUE 20): the kv
                        row runs against a counting fake PD; this row
                        re-runs the shape against a REAL placement
                        driver with the lifecycle policy loop on and
                        every actuator held idle, and must stay within
                        BENCH_GATE_LIFECYCLE_THRESHOLD (default 3%) of
                        the same-session fake-PD measurement — policy
                        evaluation at 128 regions is pure PD-side scan
                        work and must never tax the serving path.

The committed JSONs are the contract, but gate runs are SHORT (boot +
elections amortize worse over a 6 s window than over a full bench), so
each floor is derived from a same-shape calibration value stored as
``extra.gate_commits_per_sec`` / ``extra.gate_kv_ops_per_sec`` in the
respective JSON — record both with ``python bench_gate.py --record`` on
the host that runs the gate.  Without a calibration the e2e row falls
back to the full-run ``value`` (conservative); the KV row cannot (its
full run uses a different duration/region shape) and reads as broken.

A run below its floor is retried (best-of-N, default 2 extra runs)
before the gate fails: a real regression makes EVERY run slow, while a
noisy-neighbour phase on a shared host does not survive three samples.
Exit 0 = within threshold, 1 = regression, 2 = the gate itself could
not run (missing baseline, bench crash) — a broken gate must read as
failure, not as a pass.

    python bench_gate.py                 # both rows, 20%
    python bench_gate.py --record        # (re)calibrate both baselines
    BENCH_GATE_THRESHOLD=0.3 python bench_gate.py   # looser (noisy CI)
    BENCH_GATE_RETRIES=0 python bench_gate.py       # strict single run
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.abspath(__file__))


def _run_e2e_once(extra: dict, duration: float) -> float:
    """One short bench_e2e run at the committed shape; returns commits/s
    or raises RuntimeError when the bench itself fails."""
    out_path = os.path.join(tempfile.mkdtemp(prefix="tpuraft_gate_"),
                            "gate.json")
    cmd = [sys.executable, os.path.join(REPO, "bench_e2e.py"),
           "--groups", str(extra.get("groups", 64)),
           "--stores", str(extra.get("stores", 3)),
           "--window", str(extra.get("window_per_group", 8)),
           "--payload", str(extra.get("payload_bytes", 16)),
           "--duration", str(duration), "--warmup", "2",
           "--skip-brk", "--json-out", out_path]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    print("bench-gate:", " ".join(cmd), flush=True)
    rc = subprocess.call(cmd, env=env)
    if rc != 0 or not os.path.exists(out_path):
        raise RuntimeError(f"bench run failed (rc={rc})")
    with open(out_path) as f:
        return float(json.load(f)["value"])


def _run_kv_once(extra: dict, duration: float,
                 read_frac: float = -1.0,
                 trace_sample: float = 0.0,
                 heat_off: bool = False,
                 disk_guard_off: bool = False,
                 chaos_clock: bool = False,
                 lifecycle_pd: bool = False,
                 workers: int = 0) -> float:
    """One short bench_region_density run at the gate shape; returns
    KV ops/s through the full serving stack.  ``read_frac >= 0`` runs
    the read-mix shape (the amortized read plane's regression row);
    ``trace_sample > 0`` runs with product tracing sampling at that
    rate (the tracing-overhead row); ``heat_off`` disables per-region
    heat tracking (the heat-overhead row's A/B comparator);
    ``disk_guard_off`` disables the disk budget / pressure plane (the
    disk-guard-overhead row's A/B comparator); ``chaos_clock`` routes
    every store's timing reads through an injected ChaosClock at rate
    1.0 (the clock-overhead row's A/B comparator); ``lifecycle_pd``
    replaces the counting fake PD with a real placement driver whose
    lifecycle policy loop runs with every actuator held idle (the
    lifecycle-overhead row's A/B comparator)."""
    regions = int(extra.get("gate_regions", 128))
    out_path = os.path.join(tempfile.mkdtemp(prefix="tpuraft_gate_kv_"),
                            "gate_regions.json")
    cmd = [sys.executable, os.path.join(REPO, "bench_region_density.py"),
           "--regions", str(regions),
           "--duration", str(duration),
           "--election-timeout-ms", str(extra.get("gate_eto_ms", 1000)),
           "--json-out", out_path]
    key = "row" if regions == 1024 else f"row_{regions}"
    if workers > 0:
        cmd += ["--workers", str(workers)]
        if workers != 24:
            key += f"_w{workers}"
    if read_frac >= 0:
        cmd += ["--read-frac", str(read_frac)]
        key += f"_r{int(round(read_frac * 100))}"
    if trace_sample > 0:
        cmd += ["--trace-sample", str(trace_sample)]
    if heat_off:
        cmd.append("--no-heat")
        key += "_noheat"
    if disk_guard_off:
        cmd.append("--no-disk-guard")
        key += "_nodg"
    if chaos_clock:
        cmd.append("--chaos-clock")
        key += "_ck"
    if lifecycle_pd:
        cmd.append("--lifecycle-pd")
        key += "_lcpd"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    print("bench-gate:", " ".join(cmd), flush=True)
    rc = subprocess.call(cmd, env=env)
    if rc != 0 or not os.path.exists(out_path):
        raise RuntimeError(f"kv bench run failed (rc={rc})")
    with open(out_path) as f:
        data = json.load(f)
    row = data.get(key, {})
    if "ops_per_sec" not in row:
        raise RuntimeError(f"kv bench produced no {key}.ops_per_sec")
    return float(row["ops_per_sec"])


def _run_mp_once(extra: dict, duration: float) -> float:
    """One short bench_multiproc run at the gate shape: real OS-process
    stores (examples.proc_supervisor) serving the saturated pure-write
    workload over real sockets; returns cross-process KV ops/s."""
    regions = int(extra.get("gate_mp_regions", 128))
    out_path = os.path.join(tempfile.mkdtemp(prefix="tpuraft_gate_mp_"),
                            "gate_mp.json")
    cmd = [sys.executable, os.path.join(REPO, "bench_multiproc.py"),
           "--regions", str(regions),
           "--duration", str(duration),
           "--workers", "256",
           # calibration shape: long eto keeps timer-mode standing load
           # flat so the short window measures serving, not elections
           "--election-timeout-ms",
           str(extra.get("gate_mp_eto_ms", 10000)),
           "--json-out", out_path]
    key = ("row_mp" if regions == 1024 else f"row_mp_{regions}") \
        + "_w256_r0"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    print("bench-gate:", " ".join(cmd), flush=True)
    rc = subprocess.call(cmd, env=env)
    if rc != 0 or not os.path.exists(out_path):
        raise RuntimeError(f"mp bench run failed (rc={rc})")
    with open(out_path) as f:
        data = json.load(f)
    row = data.get(key, {})
    if "ops_per_sec" not in row:
        raise RuntimeError(f"mp bench produced no {key}.ops_per_sec")
    return float(row["ops_per_sec"])


def _run_engine_once(extra: dict) -> float:
    """One bench_multichip --engine-shape run: the single-device engine
    tick rate at the committed leader-heavy shape (numpy tick path, no
    mesh).  The row pins the per-tick host cost of the [G] lanes — the
    group-axis sharding work must not tax the single-device engine."""
    cmd = [sys.executable, os.path.join(REPO, "bench_multichip.py"),
           "--engine-shape",
           "--groups", str(extra.get("gate_engine_groups", 1024)),
           "--duration", str(extra.get("gate_engine_duration_s", 2.0))]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    print("bench-gate:", " ".join(cmd), flush=True)
    out = subprocess.run(cmd, env=env, capture_output=True, text=True)
    if out.returncode != 0:
        raise RuntimeError(f"engine shape bench failed "
                           f"(rc={out.returncode}): {out.stderr[-300:]}")
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return float(json.loads(
                line[len("RESULT "):])["engine_ticks_per_sec"])
    raise RuntimeError("engine shape bench produced no RESULT line")


def _gate(name: str, committed: float, run_once, threshold: float,
          retries: int) -> tuple[int, dict]:
    floor = committed * (1.0 - threshold)
    best, runs = 0.0, 0
    try:
        for attempt in range(1 + max(0, retries)):
            best = max(best, run_once())
            runs = attempt + 1
            if best >= floor:
                break
            if attempt < retries:
                print(f"bench-gate[{name}]: {best:.1f} < floor {floor:.1f}, "
                      f"retrying ({attempt + 1}/{retries})", flush=True)
    except RuntimeError as exc:
        print(f"bench-gate[{name}]: {exc}")
        return 2, {"gate": name, "verdict": "BROKEN", "error": str(exc)}
    verdict = "OK" if best >= floor else "REGRESSION"
    report = {
        "gate": name,
        "committed": committed,
        "measured": round(best, 1),
        "floor": round(floor, 1),
        "threshold": threshold,
        "runs": runs,
        "verdict": verdict,
    }
    return (0 if verdict == "OK" else 1), report


def main() -> int:
    e2e_path = os.path.join(REPO, "BENCH_E2E.json")
    kv_path = os.path.join(REPO, "BENCH_REGIONS.json")
    if not os.path.exists(e2e_path):
        print("bench-gate: no committed BENCH_E2E.json baseline")
        return 2
    with open(e2e_path) as f:
        e2e_base = json.load(f)
    kv_base = {}
    if os.path.exists(kv_path):
        with open(kv_path) as f:
            kv_base = json.load(f)
    e2e_extra = e2e_base.get("extra", {})
    kv_extra = kv_base.setdefault("extra", {})
    threshold = float(os.environ.get("BENCH_GATE_THRESHOLD", "0.20"))
    duration = float(os.environ.get("BENCH_GATE_DURATION", "6"))
    retries = int(os.environ.get("BENCH_GATE_RETRIES", "2"))

    if "--record" in sys.argv[1:]:
        # calibrate: best-of-2 short runs per row
        try:
            e2e_best = max(_run_e2e_once(e2e_extra, duration)
                           for _ in range(2))
            kv_best = max(_run_kv_once(kv_extra, duration)
                          for _ in range(2))
            read_best = max(_run_kv_once(kv_extra, duration, read_frac=0.95)
                            for _ in range(2))
            write_best = max(_run_kv_once(kv_extra, duration,
                                          read_frac=0.0, workers=256)
                             for _ in range(2))
            mp_best = max(_run_mp_once(kv_extra, duration)
                          for _ in range(2))
            engine_best = max(_run_engine_once(e2e_extra)
                              for _ in range(2))
        except RuntimeError as exc:
            print(f"bench-gate: {exc}")
            return 2
        e2e_extra["gate_commits_per_sec"] = round(e2e_best, 1)
        e2e_extra["gate_engine_ticks_per_sec"] = round(engine_best, 1)
        e2e_extra["gate_duration_s"] = duration
        e2e_base["extra"] = e2e_extra
        with open(e2e_path, "w") as f:
            json.dump(e2e_base, f, indent=1)
            f.write("\n")
        kv_extra["gate_kv_ops_per_sec"] = round(kv_best, 1)
        kv_extra["gate_read_ops_per_sec"] = round(read_best, 1)
        kv_extra["gate_write_ops_per_sec"] = round(write_best, 1)
        kv_extra["gate_mp_write_ops_per_sec"] = round(mp_best, 1)
        kv_extra["gate_duration_s"] = duration
        kv_extra.setdefault("gate_regions", 128)
        kv_extra.setdefault("gate_eto_ms", 1000)
        with open(kv_path, "w") as f:
            json.dump(kv_base, f, indent=1)
            f.write("\n")
        print(json.dumps({"gate": "recorded",
                          "gate_commits_per_sec":
                              e2e_extra["gate_commits_per_sec"],
                          "gate_engine_ticks_per_sec":
                              e2e_extra["gate_engine_ticks_per_sec"],
                          "gate_kv_ops_per_sec":
                              kv_extra["gate_kv_ops_per_sec"],
                          "gate_read_ops_per_sec":
                              kv_extra["gate_read_ops_per_sec"],
                          "gate_write_ops_per_sec":
                              kv_extra["gate_write_ops_per_sec"],
                          "gate_mp_write_ops_per_sec":
                              kv_extra["gate_mp_write_ops_per_sec"],
                          "duration_s": duration}))
        return 0

    worst = 0
    reports = []
    rc, rep = _gate("e2e_commits_per_sec",
                    float(e2e_extra.get("gate_commits_per_sec",
                                        e2e_base["value"])),
                    lambda: _run_e2e_once(e2e_extra, duration),
                    threshold, retries)
    worst = max(worst, rc)
    reports.append(rep)
    if "gate_engine_ticks_per_sec" not in e2e_extra:
        # the single-device engine shape (ISSUE 19) needs its own row:
        # the mesh-mode sharding work lands new [G] lanes in every tick
        # and this is the floor that keeps them honest on one device
        print("bench-gate[engine_ticks_per_sec]: no calibration "
              "(run `python bench_gate.py --record`)")
        worst = max(worst, 2)
        reports.append({"gate": "engine_ticks_per_sec",
                        "verdict": "BROKEN",
                        "error": "no gate_engine_ticks_per_sec "
                                 "calibration"})
    else:
        rc, rep = _gate("engine_ticks_per_sec",
                        float(e2e_extra["gate_engine_ticks_per_sec"]),
                        lambda: _run_engine_once(e2e_extra),
                        threshold, retries)
        worst = max(worst, rc)
        reports.append(rep)
    if "gate_kv_ops_per_sec" not in kv_extra:
        # no same-shape calibration — a silent pass would defeat the row
        print("bench-gate[kv_ops_per_sec]: no calibration "
              "(run `python bench_gate.py --record`)")
        worst = max(worst, 2)
        reports.append({"gate": "kv_ops_per_sec", "verdict": "BROKEN",
                        "error": "no gate_kv_ops_per_sec calibration"})
    else:
        rc, rep = _gate("kv_ops_per_sec",
                        float(kv_extra["gate_kv_ops_per_sec"]),
                        lambda: _run_kv_once(kv_extra, duration),
                        threshold, retries)
        worst = max(worst, rc)
        reports.append(rep)
        # tracing-overhead row (observability plane): the untraced kv
        # rows above ARE the zero-cost claim (tracing defaults off, so
        # any always-on cost would regress them vs calibration); this
        # row additionally bounds SAMPLED tracing at 5% of the same-
        # session untraced measurement — same host phase, so shared-
        # host noise largely cancels (retries absorb the rest)
        if rep.get("verdict") == "OK":
            trace_threshold = float(os.environ.get(
                "BENCH_GATE_TRACE_THRESHOLD", "0.05"))
            rc, trep = _gate(
                "kv_ops_traced",
                float(rep["measured"]),
                lambda: _run_kv_once(kv_extra, duration,
                                     trace_sample=0.05),
                trace_threshold, retries)
            worst = max(worst, rc)
            trep["untraced"] = rep["measured"]
            reports.append(trep)
            # heat-overhead row (fleet observability): heat tracking
            # defaults ON, so the kv row above already PAYS for heat —
            # gate it against a same-session heat-OFF run at 3%.  The
            # committed floor is the heat-off measurement (the faster
            # comparator); retries re-run the heat-ON side.
            heat_threshold = float(os.environ.get(
                "BENCH_GATE_HEAT_THRESHOLD", "0.03"))
            try:
                heat_off = _run_kv_once(kv_extra, duration,
                                        heat_off=True)
                rc, hrep = _gate(
                    "kv_ops_heat_overhead", heat_off,
                    lambda: _run_kv_once(kv_extra, duration),
                    heat_threshold, retries)
                hrep["heat_off"] = round(heat_off, 1)
            except RuntimeError as exc:
                print(f"bench-gate[kv_ops_heat_overhead]: {exc}")
                rc, hrep = 2, {"gate": "kv_ops_heat_overhead",
                               "verdict": "BROKEN", "error": str(exc)}
            worst = max(worst, rc)
            reports.append(hrep)
            # disk-guard-overhead row (ISSUE 17): the DiskBudget is
            # fed from the hot path (a couple of integer adds per
            # append/snapshot) and the shed check is one state read at
            # admission — gate the guard-ON run against a same-session
            # guard-OFF comparator at 2% so the pressure plane can
            # never grow a per-op statvfs or lock without tripping CI.
            disk_threshold = float(os.environ.get(
                "BENCH_GATE_DISK_THRESHOLD", "0.02"))
            try:
                guard_off = _run_kv_once(kv_extra, duration,
                                         disk_guard_off=True)
                rc, drep = _gate(
                    "kv_ops_disk_guard", guard_off,
                    lambda: _run_kv_once(kv_extra, duration),
                    disk_threshold, retries)
                drep["disk_guard_off"] = round(guard_off, 1)
            except RuntimeError as exc:
                print(f"bench-gate[kv_ops_disk_guard]: {exc}")
                rc, drep = 2, {"gate": "kv_ops_disk_guard",
                               "verdict": "BROKEN", "error": str(exc)}
            worst = max(worst, rc)
            reports.append(drep)
            # injected-clock-overhead row (ISSUE 18): the kv row above
            # runs on the zero-indirection SYSTEM clock; this row runs
            # the SAME shape through a per-store ChaosClock at rate
            # 1.0 (full virtual-clock arithmetic, no behavior change)
            # and must stay within 2% of the same-session uninjected
            # measurement — the clock fabric can never grow a lock or
            # a syscall per read without tripping CI.
            clock_threshold = float(os.environ.get(
                "BENCH_GATE_CLOCK_THRESHOLD", "0.02"))
            rc, crep = _gate(
                "kv_ops_clocked",
                float(rep["measured"]),
                lambda: _run_kv_once(kv_extra, duration,
                                     chaos_clock=True),
                clock_threshold, retries)
            worst = max(worst, rc)
            crep["uninjected"] = rep["measured"]
            reports.append(crep)
            # lifecycle-overhead row (ISSUE 20): the kv row above runs
            # against a counting FAKE PD; this row re-runs the SAME
            # shape against a real placement driver whose lifecycle
            # policy loop evaluates every heartbeat round with every
            # actuator held idle (split/merge/move thresholds no run
            # can cross), and must stay within 3% of the same-session
            # fake-PD measurement — the policy scan over 128 regions'
            # heat/stats can never grow per-op cost on the serving
            # path without tripping CI.
            lifecycle_threshold = float(os.environ.get(
                "BENCH_GATE_LIFECYCLE_THRESHOLD", "0.03"))
            rc, lrep = _gate(
                "kv_ops_lifecycle_overhead",
                float(rep["measured"]),
                lambda: _run_kv_once(kv_extra, duration,
                                     lifecycle_pd=True),
                lifecycle_threshold, retries)
            worst = max(worst, rc)
            lrep["fake_pd"] = rep["measured"]
            reports.append(lrep)
    if "gate_read_ops_per_sec" not in kv_extra:
        # the amortized read plane (ISSUE 10) needs its own regression
        # row — a silent pass without a calibration would defeat it
        print("bench-gate[kv_read_ops_per_sec]: no calibration "
              "(run `python bench_gate.py --record`)")
        worst = max(worst, 2)
        reports.append({"gate": "kv_read_ops_per_sec", "verdict": "BROKEN",
                        "error": "no gate_read_ops_per_sec calibration"})
    else:
        rc, rep = _gate("kv_read_ops_per_sec",
                        float(kv_extra["gate_read_ops_per_sec"]),
                        lambda: _run_kv_once(kv_extra, duration,
                                             read_frac=0.95),
                        threshold, retries)
        worst = max(worst, rc)
        reports.append(rep)
    if "gate_write_ops_per_sec" not in kv_extra:
        # the batched write plane (ISSUE 15) needs its own regression
        # row: the saturated pure-write shape (w256) exercises the
        # append rounds + eager commits + ack-at-commit pipeline the
        # default 24-worker mixed row barely touches
        print("bench-gate[kv_write_ops_per_sec]: no calibration "
              "(run `python bench_gate.py --record`)")
        worst = max(worst, 2)
        reports.append({"gate": "kv_write_ops_per_sec",
                        "verdict": "BROKEN",
                        "error": "no gate_write_ops_per_sec calibration"})
    else:
        rc, rep = _gate("kv_write_ops_per_sec",
                        float(kv_extra["gate_write_ops_per_sec"]),
                        lambda: _run_kv_once(kv_extra, duration,
                                             read_frac=0.0, workers=256),
                        threshold, retries)
        worst = max(worst, rc)
        reports.append(rep)
    if "gate_mp_write_ops_per_sec" not in kv_extra:
        # the process fabric (ISSUE 16) needs its own regression row:
        # the cross-process topology exercises READY probes, framed
        # sockets, and the drain contract that no in-process row touches
        print("bench-gate[kv_mp_write_ops_per_sec]: no calibration "
              "(run `python bench_gate.py --record`)")
        worst = max(worst, 2)
        reports.append({"gate": "kv_mp_write_ops_per_sec",
                        "verdict": "BROKEN",
                        "error": "no gate_mp_write_ops_per_sec "
                                 "calibration"})
    else:
        rc, rep = _gate("kv_mp_write_ops_per_sec",
                        float(kv_extra["gate_mp_write_ops_per_sec"]),
                        lambda: _run_mp_once(kv_extra, duration),
                        threshold, retries)
        worst = max(worst, rc)
        reports.append(rep)
    for rep in reports:
        print(json.dumps(rep))
    return worst


if __name__ == "__main__":
    sys.exit(main())
