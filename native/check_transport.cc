// Sanitizer exercise driver for the epoll transport engine
// (transport.cc).  Two engines in one process — a server echoing frames
// and a client with concurrent sender threads — exercising the I/O
// thread / host thread hand-off rings under TSAN and ASAN
// (`make -C native check-native`).

#include <poll.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* tnt_create(char* err, int errlen);
void tnt_destroy(void* h);
int tnt_notify_fd(void* h);
int tnt_listen(void* h, const char* host, int port, char* err, int errlen);
int64_t tnt_send_to(void* h, const char* endpoint, uint64_t seq,
                    uint8_t flags, const uint8_t* payload, int64_t len,
                    char* err, int errlen);
int tnt_send_conn(void* h, int64_t conn_id, uint64_t seq, uint8_t flags,
                  const uint8_t* payload, int64_t len, char* err, int errlen);
int tnt_next_event(void* h, int* type, int64_t* conn_id, uint64_t* seq,
                   uint8_t* flags, uint8_t** payload, int64_t* len,
                   char* endpoint_out, int endpoint_cap);
void tnt_free(uint8_t* p);
}

namespace {

constexpr int kSenders = 4;
constexpr int kPerSender = 500;

bool wait_readable(int fd, int timeout_ms) {
  pollfd p{fd, POLLIN, 0};
  return poll(&p, 1, timeout_ms) > 0;
}

}  // namespace

int main() {
  char err[256] = {0};
  void* server = tnt_create(err, sizeof(err));
  void* client = tnt_create(err, sizeof(err));
  if (!server || !client) {
    fprintf(stderr, "create failed: %s\n", err);
    return 1;
  }
  int port = tnt_listen(server, "127.0.0.1", 0, err, sizeof(err));
  if (port <= 0) {
    fprintf(stderr, "listen failed: %s\n", err);
    return 1;
  }
  std::string ep = "127.0.0.1:" + std::to_string(port);

  std::atomic<bool> stop{false};
  std::atomic<int> echoed{0};

  // server: drain frames, echo each back on its connection
  std::thread echo([&] {
    int nfd = tnt_notify_fd(server);
    while (!stop.load(std::memory_order_acquire)) {
      int type;
      int64_t conn_id, len;
      uint64_t seq;
      uint8_t flags;
      uint8_t* payload = nullptr;
      char epbuf[64];
      int got = tnt_next_event(server, &type, &conn_id, &seq, &flags,
                               &payload, &len, epbuf, sizeof(epbuf));
      if (!got) {
        wait_readable(nfd, 50);
        continue;
      }
      if (type == 1) {  // frame
        char e[256];
        if (tnt_send_conn(server, conn_id, seq, 1, payload, len, e,
                          sizeof(e)) != 0) {
          fprintf(stderr, "echo send failed: %s\n", e);
          abort();
        }
        echoed.fetch_add(1, std::memory_order_relaxed);
      }
      tnt_free(payload);
    }
  });

  // client: concurrent senders (the send path locks per engine), one
  // drainer counting echo responses
  std::atomic<int> acked{0};
  std::thread drain([&] {
    int nfd = tnt_notify_fd(client);
    while (acked.load(std::memory_order_acquire) < kSenders * kPerSender) {
      int type;
      int64_t conn_id, len;
      uint64_t seq;
      uint8_t flags;
      uint8_t* payload = nullptr;
      char epbuf[64];
      int got = tnt_next_event(client, &type, &conn_id, &seq, &flags,
                               &payload, &len, epbuf, sizeof(epbuf));
      if (!got) {
        if (!wait_readable(nfd, 2000)) {
          fprintf(stderr, "stalled at %d acks\n",
                  acked.load(std::memory_order_relaxed));
          abort();
        }
        continue;
      }
      if (type == 1) acked.fetch_add(1, std::memory_order_release);
      tnt_free(payload);
    }
  });

  std::vector<std::thread> senders;
  for (int s = 0; s < kSenders; ++s) {
    senders.emplace_back([&, s] {
      for (int i = 0; i < kPerSender; ++i) {
        std::string msg =
            "m" + std::to_string(s) + "-" + std::to_string(i);
        char e[256];
        if (tnt_send_to(client, ep.c_str(),
                        static_cast<uint64_t>(s) << 32 | i, 0,
                        reinterpret_cast<const uint8_t*>(msg.data()),
                        static_cast<int64_t>(msg.size()), e,
                        sizeof(e)) < 0) {
          fprintf(stderr, "send failed: %s\n", e);
          abort();
        }
      }
    });
  }

  for (auto& s : senders) s.join();
  drain.join();
  stop.store(true, std::memory_order_release);
  echo.join();
  tnt_destroy(client);
  tnt_destroy(server);
  if (echoed.load() < kSenders * kPerSender) {
    fprintf(stderr, "echoed %d < %d\n", echoed.load(),
            kSenders * kPerSender);
    return 1;
  }
  printf("check_transport OK (%d frames echoed, %d sender threads)\n",
         kSenders * kPerSender, kSenders);
  return 0;
}
